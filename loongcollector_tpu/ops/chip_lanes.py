"""loongmesh chip lanes: per-chip dispatch streams with affinity, budget
shares, breakers and chaos.

One agent process owning an ICI-connected multi-chip slice has two ways to
use it (ROADMAP open item 2, "millions of users"):

* **Full-mesh SPMD** (parallel/mesh.ShardedParsePlane): one dispatch
  stream shards every batch row-wise over all chips via ``shard_map``.
  The production default for a single dispatching worker — one stream
  saturating the whole slice.

* **Chip lanes** (this module): when the sharded processor runner has
  multiple workers, each worker binds to a home chip — ``source →
  worker`` is loongshard's CRC32 affinity hash, ``worker → chip`` is
  ``worker_id % n_chips`` — and dispatches its batches as single-device
  executions *placed* on that chip.  Distinct chips run truly independent
  execution streams (no collectives on the batch path), per-source
  ordering survives multi-device fan-out by construction (stable source →
  worker → chip chain + FIFO worker lanes), and a chip is an isolated
  fault domain:

  - **chaos**: every lane dispatch passes the fault point
    ``device_plane.chip_lane.<i>`` (the ``device_plane.chip_lane.*``
    family in the catalogue) — an injected ERROR is a single-chip fault.
  - **breaker**: each lane owns a three-state circuit
    (:class:`ChipLaneBreaker`, the sink-breaker machine with a chip-lane
    vocabulary).  Repeated lane faults trip it OPEN: the lane's shard
    **respills to host parsing** (ledger-conserved — the events still
    parse, synchronously, on the host tier) while every other chip keeps
    running.  After the cooldown one half-open probe dispatch is
    admitted; success re-closes the lane.
  - **budget**: each lane accounts its own in-flight bytes against a
    per-chip share of the DevicePlane budget, so one slow chip's backlog
    drains through its own lane instead of starving the whole plane.

Observability: per-chip MetricsRecords (category ``device_plane``,
component ``chip_lane``) carry dispatch/respill counters, row
occupancy/padding and in-flight gauges; breaker state/transition counters
ride the breaker's own record (component ``chip_lane_circuit``); the
router's :func:`status` feeds the ``mesh`` section of ``/debug/status``.

``LOONG_MESH_LANES`` forces lane routing on (=1) or off (=0); default
auto — on when more than one device is attached.  ``LOONG_MESH_CHIPS``
caps how many devices the router (and the full-mesh plane) use, which is
what the bench chips=1/2/4/8 sweep varies.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from .. import chaos
from ..monitor.alarms import AlarmType
from ..runner.circuit import BreakerState, SinkCircuitBreaker
from ..utils.logger import get_logger

log = get_logger("chip_lanes")

ENV_LANES = "LOONG_MESH_LANES"
ENV_CHIPS = "LOONG_MESH_CHIPS"
ENV_TRIP = "LOONG_LANE_TRIP_THRESHOLD"
ENV_COOLDOWN = "LOONG_LANE_COOLDOWN_S"

#: catalogue name for the per-lane fault-point family (the concrete
#: points are ``device_plane.chip_lane.<i>``, registered per lane so a
#: plan can storm one chip, a subset, or the whole slice via fnmatch)
FP_CHIP_LANE = chaos.register_point("device_plane.chip_lane")


class ChipLaneFault(chaos.ChaosFault):
    """Injected single-chip fault (``device_plane.chip_lane.<i>``).  Typed
    so the engine's drain loop can tell "this chip is faulting" (breaker
    feedback + host respill) apart from the generic async-stage chaos
    that re-runs on the same kernel."""


def mesh_chip_cap(env=os.environ) -> Optional[int]:
    """LOONG_MESH_CHIPS: cap on how many devices the lanes/mesh use
    (the bench sweep's knob).  None = all attached devices."""
    raw = env.get(ENV_CHIPS)
    if raw:
        try:
            n = int(raw)
            if n >= 1:
                return n
        except ValueError:
            pass
    return None


def lanes_enabled(env=os.environ) -> Optional[bool]:
    """Tri-state: True forced on, False forced off, None auto (on when
    more than one device is attached)."""
    raw = env.get(ENV_LANES, "").strip()
    if raw == "1":
        return True
    if raw == "0":
        return False
    return None


def _trip_threshold(env=os.environ) -> int:
    try:
        return max(1, int(env.get(ENV_TRIP, "3")))
    except ValueError:
        return 3


def _cooldown_s(env=os.environ) -> float:
    try:
        return max(0.05, float(env.get(ENV_COOLDOWN, "2.0")))
    except ValueError:
        return 2.0


class ChipLaneBreaker(SinkCircuitBreaker):
    """The three-state sink-breaker machine wearing a chip-lane identity:
    its own metric component, CHIP_LANE_OPEN alarms, and
    ``chip_lane.open/half_open/close`` flight/trace events.  OPEN means
    "this chip's shard parses on the host" — a throughput degradation,
    never a loss."""

    COMPONENT = "chip_lane_circuit"
    FLIGHT_PREFIX = "chip_lane"
    KIND = "chip lane"
    DEGRADE_NOTE = "respilling shard to host parsing"
    ALARM_TYPE = AlarmType.CHIP_LANE_OPEN


class ChipLane:
    """One chip's dispatch lane: device handle, fault point, breaker,
    per-chip telemetry and in-flight byte accounting."""

    def __init__(self, index: int, device=None):
        self.index = index
        self.device = device
        self.fault_point = chaos.register_point(
            f"device_plane.chip_lane.{index}")
        self.breaker = ChipLaneBreaker(
            f"chip{index}",
            failure_threshold=_trip_threshold(),
            cooldown_s=_cooldown_s())
        from ..monitor.metrics import MetricsRecord
        self.metrics = MetricsRecord(
            category="device_plane",
            labels={"component": "chip_lane", "chip": str(index)})
        self._dispatches = self.metrics.counter("lane_dispatches_total")
        self._respill_batches = self.metrics.counter(
            "lane_respilled_batches_total")
        self._respill_events = self.metrics.counter(
            "lane_respilled_events_total")
        self._faults = self.metrics.counter("lane_faults_total")
        self._rows_real = self.metrics.counter("lane_rows_real_total")
        self._rows_padded = self.metrics.counter("lane_rows_padded_total")
        self._inflight_gauge = self.metrics.gauge("lane_inflight_bytes")
        self._occupancy_gauge = self.metrics.gauge("lane_row_occupancy")
        self._state_gauge = self.metrics.gauge("lane_breaker_state")
        self._lock = threading.Lock()
        self._inflight = 0
        self._respilled_events_n = 0

    # -- dispatch accounting -------------------------------------------------

    def note_pack(self, B: int, n_real: int) -> None:
        self._dispatches.add(1)
        self._rows_real.add(n_real)
        self._rows_padded.add(B - n_real)
        self._occupancy_gauge.set(n_real / B if B else 0.0)

    def note_dispatch(self, nbytes: int) -> None:
        with self._lock:
            self._inflight += nbytes
            self._inflight_gauge.set(float(self._inflight))

    def note_done(self, nbytes: int) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - nbytes)
            self._inflight_gauge.set(float(self._inflight))

    def inflight_bytes(self) -> int:
        with self._lock:
            return self._inflight

    def note_fault(self) -> None:
        self._faults.add(1)

    def note_respill(self, n_events: int) -> None:
        self._respill_batches.add(1)
        self._respill_events.add(n_events)
        with self._lock:
            self._respilled_events_n += n_events

    def respilled_events(self) -> int:
        with self._lock:
            return self._respilled_events_n

    # -- budget share --------------------------------------------------------

    def over_share(self, plane, lane_count: int) -> bool:
        """True when this lane holds more than its per-chip share of the
        plane budget — the dispatcher drains its own oldest chunk first
        (same never-sleep-owning-budget discipline, per chip)."""
        if lane_count <= 1 or not plane.budget_bytes:
            return False
        share = plane.budget_bytes // lane_count
        with self._lock:
            return self._inflight > share

    def mark_deleted(self) -> None:
        """Retire this lane's metric records (router rebuild) — they must
        not accumulate in WriteMetrics across reconfigurations."""
        self.metrics.mark_deleted()
        self.breaker.mark_deleted()

    # -- observability -------------------------------------------------------

    def breaker_state(self) -> BreakerState:
        st = self.breaker.state
        self._state_gauge.set(float(st))
        return st

    def status(self) -> dict:
        return {
            "chip": self.index,
            "device": str(self.device) if self.device is not None else None,
            "breaker": self.breaker_state().name,
            "inflight_bytes": self.inflight_bytes(),
            "dispatches": self._dispatches.value,
            "rows_real": self._rows_real.value,
            "rows_padded": self._rows_padded.value,
            "respilled_batches": self._respill_batches.value,
            "respilled_events": self._respill_events.value,
            "faults": self._faults.value,
        }


def lane_gated(lane: ChipLane, kernel):
    """Wrap a lane's kernel call so dispatch passes the lane's chaos point
    (an injected ERROR raises :class:`ChipLaneFault` — a single-chip fault
    at dispatch).  Mirrors :func:`device_stream.h2d_gated`: the wrapper is
    what the plane submits, the bare kernel is what re-runs use, so an
    injected fault never re-fires on the recovery path."""
    fp = lane.fault_point

    def _gated(*args):
        chaos.faultpoint(fp, exc=ChipLaneFault)
        return kernel(*args)
    return _gated


class ChipLaneRouter:
    """Process-wide chip-lane registry: device discovery, worker→lane
    binding, and the status document."""

    def __init__(self, devices: Optional[list] = None):
        if devices is None:
            devices = self._discover()
        cap = mesh_chip_cap()
        if cap is not None:
            devices = devices[:cap]
        forced = lanes_enabled()
        active = forced if forced is not None else len(devices) > 1
        self.lanes: List[ChipLane] = (
            [ChipLane(i, d) for i, d in enumerate(devices)] if active
            else [])

    @staticmethod
    def _discover() -> list:
        try:
            import jax
            return list(jax.devices())
        except Exception:  # noqa: BLE001 — no backend ⇒ no lanes
            return []

    def lane_count(self) -> int:
        return len(self.lanes)

    def lane_for_worker(self, worker_id: int) -> Optional[ChipLane]:
        """The home chip of a processor worker (``worker_id % n_chips``).
        None when lane routing is inactive (≤1 device, or forced off) —
        the caller then stays on the full-mesh/single-device path."""
        if len(self.lanes) <= 1:
            return None
        return self.lanes[worker_id % len(self.lanes)]

    def lane_for_source(self, queue_key: int, source: Optional[bytes],
                        n_workers: int) -> Optional[ChipLane]:
        """source → worker → chip: the full affinity chain, exposed for
        determinism assertions and operator tooling.  Same CRC32 hash as
        loongshard's worker routing, so the chip a source lands on is
        stable across runs and processes."""
        from ..runner.processor_runner import shard_of
        return self.lane_for_worker(shard_of(queue_key, source, n_workers))

    def status(self) -> dict:
        return {
            "lane_count": self.lane_count(),
            "lanes": [lane.status() for lane in self.lanes],
        }


_router: Optional[ChipLaneRouter] = None
_router_lock = threading.Lock()
_tls = threading.local()


def router() -> ChipLaneRouter:
    global _router
    if _router is None:
        with _router_lock:
            if _router is None:
                _router = ChipLaneRouter()
    return _router


def active_router() -> Optional[ChipLaneRouter]:
    """Observe-only handle (never constructs): /debug/status uses this."""
    return _router


def reset_for_testing(devices: Optional[list] = None) -> ChipLaneRouter:
    """Rebuild the router (env caps / thresholds re-read); retires the old
    lanes' metric records so WriteMetrics does not accumulate them."""
    global _router
    with _router_lock:
        if _router is not None:
            for lane in _router.lanes:
                lane.mark_deleted()
        _router = ChipLaneRouter(devices)
        return _router


def set_thread_lane(lane: Optional[ChipLane]) -> None:
    """Bind THIS thread's dispatches to a chip lane (processor workers do
    this at loop entry; None unbinds on exit)."""
    _tls.lane = lane


def current_lane() -> Optional[ChipLane]:
    return getattr(_tls, "lane", None)
