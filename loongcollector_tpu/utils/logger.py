"""Agent logger (reference: core/logger/Logger.cpp — spdlog, config driven)."""

from __future__ import annotations

import logging
import os
import sys

_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    level = os.environ.get("LOONG_LOG_LEVEL", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
            datefmt="%Y-%m-%d %H:%M:%S",
        )
    )
    root = logging.getLogger("loong")
    root.setLevel(getattr(logging, level, logging.INFO))
    root.addHandler(handler)
    root.propagate = False
    _configured = True


def get_logger(name: str = "loong") -> logging.Logger:
    _configure()
    if not name.startswith("loong"):
        name = "loong." + name
    return logging.getLogger(name)
