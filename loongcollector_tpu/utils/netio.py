"""Shared blocking-socket helpers for the hand-rolled wire protocols
(Kafka, Pulsar, lumberjack)."""

from __future__ import annotations


def read_exact(sock, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf
