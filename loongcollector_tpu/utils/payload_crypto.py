"""At-rest encryption for spilled send buffers.

Reference: core/plugin/flusher/sls/DiskBufferWriter.h:56 — payloads that
spill to disk (endpoint down, agent exiting) are encrypted so a host-level
reader of the buffer directory cannot recover log content.

Construction (stdlib-only; no AES in hashlib): counter-mode stream cipher
with HMAC-SHA256 as the PRF, plus an encrypt-then-MAC integrity tag:

    keystream_i = HMAC(enc_key, nonce || be64(i))          (32 B per block)
    ct          = data XOR keystream
    tag         = HMAC(mac_key, nonce || ct)
    blob        = magic(4) || nonce(16) || tag(32) || ct

enc_key/mac_key are derived from one 32-byte master key (created on first
use, file mode 0600) via HMAC domain separation.  HMAC-CTR is a standard
PRF-counter-mode construction; throughput is ~30 MB/s in CPython — far
above the spill path's needs.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Optional

_MAGIC = b"LCE1"
_NONCE_LEN = 16
_TAG_LEN = 32
_BLOCK = 32  # SHA-256 digest size


def _derive(master: bytes, label: bytes) -> bytes:
    return hmac.new(master, label, hashlib.sha256).digest()


def _keystream(enc_key: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    for i in range((n + _BLOCK - 1) // _BLOCK):
        out += hmac.new(enc_key, nonce + struct.pack(">Q", i),
                        hashlib.sha256).digest()
    return bytes(out[:n])


def _xor(data: bytes, ks: bytes) -> bytes:
    return (int.from_bytes(data, "little")
            ^ int.from_bytes(ks, "little")).to_bytes(len(data), "little")


class PayloadCipher:
    """Encrypt/decrypt spill payloads with a host-local master key."""

    def __init__(self, key_path: str):
        self.key_path = key_path
        master = self._load_or_create_key()
        self._enc_key = _derive(master, b"loongcollector-spill-enc")
        self._mac_key = _derive(master, b"loongcollector-spill-mac")

    def _load_or_create_key(self) -> bytes:
        """Create the key ONLY when it genuinely does not exist.  Any other
        failure (permissions, truncation) raises: silently rotating the key
        would make every previously spilled payload permanently
        undecryptable — worse than failing loudly."""
        try:
            with open(self.key_path, "rb") as f:
                key = f.read()
        except FileNotFoundError:
            key = None
        if key is not None:
            if len(key) != 32:
                raise ValueError(
                    f"spill key file {self.key_path} is malformed "
                    f"({len(key)} bytes, want 32); refusing to rotate — "
                    f"restore or delete it explicitly")
            return key
        key = os.urandom(32)
        d = os.path.dirname(self.key_path)
        if d:
            os.makedirs(d, exist_ok=True)
        fd = os.open(self.key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                     0o600)
        try:
            os.write(fd, key)
        finally:
            os.close(fd)
        return key

    def encrypt(self, data: bytes) -> bytes:
        nonce = os.urandom(_NONCE_LEN)
        ct = _xor(data, _keystream(self._enc_key, nonce, len(data)))
        tag = hmac.new(self._mac_key, nonce + ct, hashlib.sha256).digest()
        return _MAGIC + nonce + tag + ct

    def decrypt(self, blob: bytes) -> Optional[bytes]:
        """None on wrong magic, truncation, or MAC mismatch."""
        if len(blob) < len(_MAGIC) + _NONCE_LEN + _TAG_LEN \
                or not blob.startswith(_MAGIC):
            return None
        off = len(_MAGIC)
        nonce = blob[off:off + _NONCE_LEN]
        tag = blob[off + _NONCE_LEN:off + _NONCE_LEN + _TAG_LEN]
        ct = blob[off + _NONCE_LEN + _TAG_LEN:]
        want = hmac.new(self._mac_key, nonce + ct, hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            return None
        return _xor(ct, _keystream(self._enc_key, nonce, len(ct)))
