"""Process-wide flag registry.

Equivalent of the reference's gflags macro layer (core/common/Flags.h:21-55):
compile-time defaults, overridable from the environment (``LOONG_<NAME>``) and
at runtime (AppConfig hot-reload callbacks re-set flags).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List


@dataclass
class _Flag:
    name: str
    value: Any
    default: Any
    typ: type
    help: str
    callbacks: List[Callable[[Any], None]]


_registry: Dict[str, _Flag] = {}
_lock = threading.Lock()


def _define(name: str, default: Any, typ: type, help_: str) -> None:
    with _lock:
        if name in _registry:
            return
        value = default
        env = os.environ.get("LOONG_" + name.upper())
        if env is not None:
            if typ is bool:
                value = env.lower() in ("1", "true", "yes", "on")
            else:
                value = typ(env)
        _registry[name] = _Flag(name, value, default, typ, help_, [])


def DEFINE_FLAG_INT32(name: str, help_: str, default: int) -> None:
    _define(name, int(default), int, help_)


def DEFINE_FLAG_INT64(name: str, help_: str, default: int) -> None:
    _define(name, int(default), int, help_)


def DEFINE_FLAG_BOOL(name: str, help_: str, default: bool) -> None:
    _define(name, bool(default), bool, help_)


def DEFINE_FLAG_DOUBLE(name: str, help_: str, default: float) -> None:
    _define(name, float(default), float, help_)


def DEFINE_FLAG_STRING(name: str, help_: str, default: str) -> None:
    _define(name, str(default), str, help_)


def get_flag(name: str) -> Any:
    return _registry[name].value


def has_flag(name: str) -> bool:
    return name in _registry


def set_flag(name: str, value: Any) -> None:
    with _lock:
        flag = _registry[name]
        if flag.typ is bool and isinstance(value, str):
            value = value.lower() in ("1", "true", "yes", "on")
        flag.value = flag.typ(value)
        callbacks = list(flag.callbacks)
    for cb in callbacks:
        cb(value)


def on_flag_change(name: str, callback: Callable[[Any], None]) -> None:
    """Register a hot-reload callback (reference: AppConfig callback registry,
    core/app_config/AppConfig.cpp + runner/FlusherRunner.cpp:43-44)."""
    with _lock:
        _registry[name].callbacks.append(callback)


def all_flags() -> Dict[str, Any]:
    with _lock:
        return {k: f.value for k, f in _registry.items()}
