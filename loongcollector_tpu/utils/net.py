"""Small shared network helpers."""

from __future__ import annotations

from typing import Tuple


def host_port(target: str, default_port: int) -> Tuple[str, int]:
    """Parse 'host', 'host:port' or '[v6]:port' (bare v6 literals need
    brackets; an unbracketed one falls back to the default port whole)."""
    if target.startswith("["):
        host, _, rest = target[1:].partition("]")
        port = rest.lstrip(":")
        return host, int(port) if port.isdigit() else default_port
    host, sep, port = target.rpartition(":")
    if sep and port.isdigit() and ":" not in host:
        return host, int(port)
    return target, default_port
