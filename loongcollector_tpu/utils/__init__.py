from .flags import (DEFINE_FLAG_BOOL, DEFINE_FLAG_DOUBLE, DEFINE_FLAG_INT32,
                    DEFINE_FLAG_INT64, DEFINE_FLAG_STRING, get_flag, set_flag)
from .logger import get_logger
from .stringview import StringView
