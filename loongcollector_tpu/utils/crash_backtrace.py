"""Crash backtrace capture & restart reporting.

Reference: core/common/CrashBackTraceUtil.cpp + Application.cpp:146-154 —
a crash writes the backtrace to a file; the next start finds it, raises the
restart alarm with the trace, and archives it.

Python implementation: `faulthandler` streams fatal-signal tracebacks into
<data_dir>/backtrace.log; `check_previous_crash` runs at startup.
"""

from __future__ import annotations

import faulthandler
import os
from typing import Optional

from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from .logger import get_logger

log = get_logger("crash")

_trace_file = None  # keep the fd alive for faulthandler


def init_crash_backtrace(data_dir: str) -> None:
    global _trace_file
    path = os.path.join(data_dir, "backtrace.log")
    os.makedirs(data_dir, exist_ok=True)
    _trace_file = open(path, "w")
    faulthandler.enable(file=_trace_file)


def record_crash(data_dir: str, trace: str) -> None:
    """Persist a Python-level crash trace for the next start's report (the
    same file faulthandler streams fatal signals into)."""
    try:
        with open(os.path.join(data_dir, "backtrace.log"), "w") as f:
            f.write(trace)
    except OSError:
        pass


def check_previous_crash(data_dir: str) -> Optional[str]:
    """If the last run crashed, report it and archive the trace."""
    path = os.path.join(data_dir, "backtrace.log")
    try:
        with open(path) as f:
            trace = f.read().strip()
    except OSError:
        return None
    if not trace:
        return None
    log.error("previous run crashed:\n%s", trace[:2000])
    AlarmManager.instance().send_alarm(
        AlarmType.AGENT_RESTART, "agent restarted after crash",
        AlarmLevel.CRITICAL)
    try:
        os.replace(path, path + ".last")
    except OSError:
        pass
    return trace
