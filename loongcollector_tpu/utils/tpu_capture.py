"""DEAD→ALIVE transition capture: on-silicon validation with no human.

The TPU tunnel in this deployment dies for hours at a time (47/47 DEAD
probes across round 4).  scripts/tpu_watch.sh maintains /tmp/tpu_alive;
this module is what that liveness signal *drives*: on every DEAD→ALIVE
transition the watcher invokes `capture()`, which runs

1. a Pallas compile+run smoke on the real chip (the Mosaic fixes from
   rounds 2-3 finally get an automated pass/fail record),
2. bench.py on the live backend (bench itself persists
   BENCH_TPU_LAST_GOOD.json, including kernel_pallas_MBps, on a
   non-degraded TPU run),
3. dryrun_multichip on the 8-device virtual CPU mesh (validating the
   sharded path against the same code state the chip window measured),

and writes a TPU_CAPTURE_LAST.json summary.  Every piece is injectable so
tests can dry-run the full trigger path without hardware.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Callable, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PALLAS_SMOKE_CODE = r"""
import json, time
import numpy as np
import jax
d = jax.devices()[0]
assert d.platform == "tpu", f"not a TPU: {d.platform}"
from loongcollector_tpu.ops.regex.program import compile_tier1
from loongcollector_tpu.ops.kernels.field_extract_pallas import \
    PallasExtractKernel
from loongcollector_tpu.ops.device_batch import pack_rows
prog = compile_tier1(r"(\S+) (\S+) (\d+)")
k = PallasExtractKernel(prog)
line = b"1.2.3.4 GET 200"
n = 4096
arena = np.frombuffer(line * n, np.uint8).copy()
off = np.arange(n, dtype=np.int64) * len(line)
ln = np.full(n, len(line), np.int32)
batch = pack_rows(arena, off, ln, 128)
ok, co, cl = (np.asarray(a) for a in k(batch.rows, batch.lengths))
assert ok[:n].all(), "pallas kernel wrong on TPU"
reps = 20
t0 = time.perf_counter()
for _ in range(reps):
    ok, co, cl = k(batch.rows, batch.lengths)
np.asarray(ok)
dt = time.perf_counter() - t0
print("PALLAS_OK", json.dumps(
    {"MBps": round(n * len(line) * reps / dt / 1e6, 1)}))
"""


class TransitionTracker:
    """Edge detector for the watcher loop: fires exactly on DEAD→ALIVE
    (including a watcher that starts during an alive window — the first
    observation counts as a transition, so an availability window is never
    wasted just because the watcher restarted inside it)."""

    def __init__(self) -> None:
        self.prev: Optional[bool] = None

    def update(self, alive: bool) -> bool:
        fired = alive and self.prev is not True
        self.prev = alive
        return fired


def pallas_smoke(run: Callable = subprocess.run, timeout: float = 900.0
                 ) -> dict:
    """Compile + run the fused Pallas extract kernel on the real chip in a
    subprocess (a wedged tunnel hangs, so never in-process)."""
    try:
        r = run([sys.executable, "-c", PALLAS_SMOKE_CODE],
                capture_output=True, timeout=timeout, text=True, cwd=REPO)
    except Exception as e:  # noqa: BLE001 — incl. TimeoutExpired
        return {"ok": False, "error": repr(e)}
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("PALLAS_OK"):
            out = {"ok": True}
            out.update(json.loads(ln.split(" ", 1)[1]))
            return out
    return {"ok": False, "error": (r.stderr or "")[-2000:],
            "rc": r.returncode}


def run_bench(run: Callable = subprocess.run, timeout: float = 1800.0
              ) -> dict:
    """bench.py on the live default backend.  bench.py itself persists
    BENCH_TPU_LAST_GOOD.json when it completes non-degraded on a TPU."""
    try:
        r = run([sys.executable, os.path.join(REPO, "bench.py")],
                capture_output=True, timeout=timeout, text=True, cwd=REPO)
    except Exception as e:  # noqa: BLE001
        return {"ok": False, "error": repr(e)}
    line = None
    for ln in (r.stdout or "").splitlines():
        if ln.strip().startswith("{"):
            line = ln.strip()
    if r.returncode != 0 or line is None:
        return {"ok": False, "rc": r.returncode,
                "error": (r.stderr or "")[-2000:]}
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        return {"ok": False, "error": "unparseable bench line"}
    return {"ok": True, "value": doc.get("value"),
            "degraded": bool(doc.get("extra", {}).get("device_degraded")),
            "device": doc.get("extra", {}).get("device")}


def run_dryrun_multichip(run: Callable = subprocess.run,
                         timeout: float = 900.0, n_devices: int = 8) -> dict:
    """dryrun_multichip on a virtual CPU mesh — same contract the driver
    checks, revalidated inside every chip window."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        f" --xla_force_host_platform_device_count={n_devices}"
                        ).strip()
    code = (f"import __graft_entry__ as g; g.dryrun_multichip({n_devices}); "
            "print('DRYRUN_OK')")
    try:
        r = run([sys.executable, "-c", code], capture_output=True,
                timeout=timeout, text=True, cwd=REPO, env=env)
    except Exception as e:  # noqa: BLE001
        return {"ok": False, "error": repr(e)}
    ok = r.returncode == 0 and "DRYRUN_OK" in (r.stdout or "")
    out = {"ok": ok}
    if not ok:
        out["rc"] = r.returncode
        out["error"] = (r.stderr or "")[-2000:]
    return out


def capture(run: Callable = subprocess.run, log: Callable = print,
            repo: str = REPO) -> dict:
    """The DEAD→ALIVE payload.  Returns (and persists) the summary."""
    summary = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    log("tpu_capture: pallas smoke...")
    summary["pallas"] = pallas_smoke(run)
    log(f"tpu_capture: pallas -> {summary['pallas']}")
    log("tpu_capture: bench.py...")
    summary["bench"] = run_bench(run)
    log(f"tpu_capture: bench -> {summary['bench']}")
    log("tpu_capture: dryrun_multichip...")
    summary["dryrun_multichip"] = run_dryrun_multichip(run)
    log(f"tpu_capture: dryrun -> {summary['dryrun_multichip']}")
    try:
        with open(os.path.join(repo, "TPU_CAPTURE_LAST.json"), "w") as f:
            json.dump(summary, f, indent=1)
    except OSError as e:
        log(f"tpu_capture: could not persist summary: {e!r}")
    return summary


def main() -> int:
    s = capture()
    ok = s["pallas"].get("ok") and s["bench"].get("ok") \
        and not s["bench"].get("degraded")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
