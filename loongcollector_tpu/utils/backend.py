"""Device-backend probing and fail-soft CPU fallback.

The TPU backend in this deployment rides an experimental `axon` platform
over a network tunnel. When that tunnel is wedged, the first jax op HANGS
(the PJRT client blocks dialing a dead relay) rather than raising — so any
in-process check would wedge with it. The probe therefore runs a real op in
a SUBPROCESS with a deadline and the caller downgrades to CPU on failure.

Reference analogue: the agent must keep collecting when a sink/backend is
unreachable (SURVEY.md §5.3 failure recovery); a parse accelerator outage
degrades throughput, never liveness.
"""

from __future__ import annotations

import os
import subprocess
import sys

from .logger import get_logger

log = get_logger("backend")

_probe_result: bool | None = None


def cpu_pinned() -> bool:
    """True when the operator explicitly pinned the CPU backend.  The
    axon platform force-registers itself at interpreter start, so the
    JAX_PLATFORMS env var alone does NOT take effect — callers must also
    update jax.config (ensure_live_backend does).  An explicit pin skips
    the tunnel probe entirely: 90 s probing a backend the user opted out
    of is pure startup latency."""
    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" \
        or os.environ.get("LOONG_BACKEND", "").strip().lower() == "cpu"


def probe_default_backend(timeout: float = 90.0) -> bool:
    """True iff the default jax backend completes a real op in time.

    Result is cached for the process lifetime (the probe costs a subprocess
    interpreter start + possible 20-40 s first compile).
    """
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices()[0];"
            "jnp.zeros(8).block_until_ready();"
            "print('OK', d.platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, timeout=timeout, text=True)
        _probe_result = r.returncode == 0 and "OK" in r.stdout
    except Exception as e:  # noqa: BLE001  (incl. TimeoutExpired)
        log.warning("backend probe failed: %r", e)
        _probe_result = False
    return _probe_result


def ensure_live_backend(timeout: float = 90.0) -> bool:
    """Downgrade jax to CPU if the default backend is unreachable.

    Returns True when running degraded (CPU fallback), False when the
    default backend is healthy. Must run BEFORE the first jax op.
    An explicit CPU pin (JAX_PLATFORMS=cpu / LOONG_BACKEND=cpu) is applied
    directly and is NOT degraded — the operator chose it.
    """
    if cpu_pinned():
        import jax
        jax.config.update("jax_platforms", "cpu")
        log.info("CPU backend pinned by operator; skipping device probe")
        return False
    if probe_default_backend(timeout):
        return False
    import jax
    jax.config.update("jax_platforms", "cpu")
    log.warning("device backend unreachable; running degraded on CPU")
    return True
