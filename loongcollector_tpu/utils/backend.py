"""Device-backend probing and fail-soft CPU fallback.

The TPU backend in this deployment rides an experimental `axon` platform
over a network tunnel. When that tunnel is wedged, the first jax op HANGS
(the PJRT client blocks dialing a dead relay) rather than raising — so any
in-process check would wedge with it. The probe therefore runs a real op in
a SUBPROCESS with a deadline and the caller downgrades to CPU on failure.

Reference analogue: the agent must keep collecting when a sink/backend is
unreachable (SURVEY.md §5.3 failure recovery); a parse accelerator outage
degrades throughput, never liveness.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

from .logger import get_logger

log = get_logger("backend")

_probe_result: bool | None = None


def alive_file_path() -> str:
    return os.environ.get("LOONG_TPU_ALIVE_FILE", "/tmp/tpu_alive")


def watch_log_path() -> str:
    return os.environ.get("LOONG_TPU_WATCH_LOG", "/tmp/tpu_watch.log")


def watcher_verdict(max_age_s: float = 360.0) -> str:
    """Instant liveness answer from the out-of-process tunnel watcher
    (scripts/tpu_watch.sh probes every ~2 min; it touches the alive file on
    a live probe and removes it on a dead one, appending to the watch log
    either way).

    'alive'   — alive file fresh: the backend answered within max_age_s;
    'dead'    — watch log fresh but no fresh alive file: the watcher is
                running and its last probes failed;
    'unknown' — no watcher evidence: fall back to an in-line probe.

    A dead tunnel used to cost every fresh process a 90 s probe timeout
    (VERDICT r4 weak #7); with a running watcher the answer is free."""
    now = time.time()
    try:
        if now - os.path.getmtime(alive_file_path()) <= max_age_s:
            return "alive"
    except OSError:
        pass
    try:
        if now - os.path.getmtime(watch_log_path()) <= max_age_s:
            return "dead"
    except OSError:
        pass
    return "unknown"


def cpu_pinned() -> bool:
    """True when the operator explicitly pinned the CPU backend.  The
    axon platform force-registers itself at interpreter start, so the
    JAX_PLATFORMS env var alone does NOT take effect — callers must also
    update jax.config (ensure_live_backend does).  An explicit pin skips
    the tunnel probe entirely: 90 s probing a backend the user opted out
    of is pure startup latency."""
    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" \
        or os.environ.get("LOONG_BACKEND", "").strip().lower() == "cpu"


def probe_default_backend(timeout: float = 90.0) -> bool:
    """True iff the default jax backend completes a real op in time.

    Result is cached for the process lifetime (the probe costs a subprocess
    interpreter start + possible 20-40 s first compile).
    """
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    verdict = watcher_verdict()
    if verdict == "alive":
        log.info("tunnel watcher reports backend ALIVE; skipping probe")
        _probe_result = True
        return True
    if verdict == "dead":
        log.warning("tunnel watcher reports backend DEAD; degrading "
                    "without probing")
        _probe_result = False
        return False
    # no watcher running: probe in-line, optionally retrying across a
    # window (LOONG_BACKEND_RETRY_WINDOW_S) so a tunnel that flaps back
    # mid-startup is still caught instead of pinning the process to CPU
    try:
        window = float(os.environ.get("LOONG_BACKEND_RETRY_WINDOW_S", "0"))
    except ValueError:
        window = 0.0
    deadline = time.monotonic() + window
    while True:
        _probe_result = _subprocess_probe(timeout)
        if _probe_result or time.monotonic() >= deadline:
            return _probe_result
        log.warning("backend probe failed; retrying (%.0f s left in window)",
                    deadline - time.monotonic())
        time.sleep(min(15.0, max(0.0, deadline - time.monotonic())))


def _subprocess_probe(timeout: float) -> bool:
    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices()[0];"
            "jnp.zeros(8).block_until_ready();"
            "print('OK', d.platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, timeout=timeout, text=True)
        return r.returncode == 0 and "OK" in r.stdout
    except Exception as e:  # noqa: BLE001  (incl. TimeoutExpired)
        log.warning("backend probe failed: %r", e)
        return False


def ensure_live_backend(timeout: float = 90.0) -> bool:
    """Downgrade jax to CPU if the default backend is unreachable.

    Returns True when running degraded (CPU fallback), False when the
    default backend is healthy. Must run BEFORE the first jax op.
    An explicit CPU pin (JAX_PLATFORMS=cpu / LOONG_BACKEND=cpu) is applied
    directly and is NOT degraded — the operator chose it.
    """
    if cpu_pinned():
        import jax
        jax.config.update("jax_platforms", "cpu")
        log.info("CPU backend pinned by operator; skipping device probe")
        return False
    if probe_default_backend(timeout):
        return False
    import jax
    jax.config.update("jax_platforms", "cpu")
    log.warning("device backend unreachable; running degraded on CPU")
    return True
