"""Zero-copy string views into a SourceBuffer arena.

Reference: core/common/StringView.h + StringBuffer (core/common/memory/
SourceBuffer.h).  Events never own their bytes; they hold (arena, offset,
length) triples.  The arena itself is a contiguous buffer that can be
transferred to TPU HBM in one copy, and device kernels return (offset, length)
spans that become new StringViews into the *same* arena — zero-copy end to end.
"""

from __future__ import annotations

from typing import Union


class StringView:
    """A (buffer, offset, length) span. Buffer is anything supporting
    __getitem__ slicing to bytes (SourceBuffer, bytes, bytearray, memoryview).
    """

    __slots__ = ("_buf", "offset", "length")

    def __init__(self, buf, offset: int = 0, length: int = -1):
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        self._buf = buf
        self.offset = offset
        if length < 0:
            length = len(buf) - offset
        self.length = length

    def to_bytes(self) -> bytes:
        buf = self._buf
        # SourceBuffer exposes .raw (bytearray); plain bytes-like slices direct.
        raw = getattr(buf, "raw", buf)
        return bytes(raw[self.offset : self.offset + self.length])

    def to_str(self) -> str:
        return self.to_bytes().decode("utf-8", errors="replace")

    @property
    def buffer(self):
        return self._buf

    def substr(self, start: int, length: int = -1) -> "StringView":
        start = max(0, min(start, self.length))
        if length < 0 or start + length > self.length:
            length = self.length - start
        return StringView(self._buf, self.offset + start, length)

    def __len__(self) -> int:
        return self.length

    def __bool__(self) -> bool:
        return self.length > 0

    def __bytes__(self) -> bytes:
        return self.to_bytes()

    def __str__(self) -> str:
        return self.to_str()

    def __eq__(self, other) -> bool:
        if isinstance(other, StringView):
            return self.to_bytes() == other.to_bytes()
        if isinstance(other, bytes):
            return self.to_bytes() == other
        if isinstance(other, str):
            return self.to_str() == other
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.to_bytes())

    def __repr__(self) -> str:
        return f"StringView({self.to_bytes()!r})"


AnyStr = Union[StringView, bytes, str]


def as_bytes(s: AnyStr) -> bytes:
    if isinstance(s, StringView):
        return s.to_bytes()
    if isinstance(s, str):
        return s.encode("utf-8")
    return bytes(s)
