"""Minimal MaxMind DB (.mmdb) reader for processor_geoip.

Reference: plugins/processor/geoip/processor_geoip.go opens the database
with the oschwald/geoip2 library; this runtime has no geoip package, so
the public MMDB binary format is read directly: metadata map located via
the \\xAB\\xCD\\xEFMaxMind.com marker, binary search tree walk (24/28/32-bit
records, IPv4-in-IPv6 handling), and the typed data section (pointers,
utf8 strings, doubles/floats, uints, maps, arrays, booleans).

Read-only and dependency-free; tests build fixture databases with the
writer in tests/test_longtail_processors.py.
"""

from __future__ import annotations

import ipaddress
import struct
from typing import Any, Optional, Tuple

_MARKER = b"\xab\xcd\xefMaxMind.com"


class MMDBError(Exception):
    pass


class Reader:
    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.buf = f.read()
        idx = self.buf.rfind(_MARKER)
        if idx < 0:
            raise MMDBError("no MaxMind metadata marker")
        meta_start = idx + len(_MARKER)
        self.data_start: Optional[int] = None   # pointers invalid until set
        self.metadata, _ = self._decode(meta_start)
        try:
            self.node_count = int(self.metadata["node_count"])
            self.record_size = int(self.metadata["record_size"])
            self.ip_version = int(self.metadata.get("ip_version", 6))
        except (KeyError, TypeError) as e:
            raise MMDBError(f"bad metadata: {e}") from e
        if self.record_size not in (24, 28, 32):
            raise MMDBError(f"unsupported record size {self.record_size}")
        self.tree_size = self.node_count * self.record_size * 2 // 8
        self.data_start = self.tree_size + 16

    # -- tree walk -----------------------------------------------------------

    def _record(self, node: int, side: int) -> int:
        rs = self.record_size
        base = node * rs * 2 // 8
        b = self.buf
        if rs == 24:
            o = base + side * 3
            return (b[o] << 16) | (b[o + 1] << 8) | b[o + 2]
        if rs == 32:
            o = base + side * 4
            return struct.unpack_from(">I", b, o)[0]
        # 28-bit: 7 bytes per node, middle byte shared
        if side == 0:
            return ((b[base + 3] & 0xF0) << 20) | (b[base] << 16) \
                | (b[base + 1] << 8) | b[base + 2]
        return ((b[base + 3] & 0x0F) << 24) | (b[base + 4] << 16) \
            | (b[base + 5] << 8) | b[base + 6]

    def lookup(self, ip: str) -> Optional[dict]:
        try:
            addr = ipaddress.ip_address(ip.strip())
        except ValueError:
            return None
        if addr.version == 6 and self.ip_version == 4:
            return None
        if addr.version == 4 and self.ip_version == 6:
            bits = 128
            value = int(ipaddress.IPv6Address("::" + str(addr)))
        else:
            bits = 32 if addr.version == 4 else 128
            value = int(addr)
        node = 0
        for i in range(bits - 1, -1, -1):
            if node >= self.node_count:
                break
            node = self._record(node, (value >> i) & 1)
        if node == self.node_count:
            return None                  # explicit no-data record
        if node < self.node_count:
            return None                  # ran out of bits (malformed tree)
        offset = node - self.node_count + self.tree_size
        out, _ = self._decode(offset)
        return out if isinstance(out, dict) else None

    # -- data section decoding ------------------------------------------------

    def _decode(self, pos: int) -> Tuple[Any, int]:
        b = self.buf
        ctrl = b[pos]
        pos += 1
        dtype = ctrl >> 5
        if dtype == 1:                   # pointer
            psize = ((ctrl >> 3) & 0x3) + 1
            v = ctrl & 0x7
            if psize == 1:
                v = (v << 8) | b[pos]
            elif psize == 2:
                v = ((v << 16) | (b[pos] << 8) | b[pos + 1]) + 2048
            elif psize == 3:
                v = ((v << 24) | (b[pos] << 16) | (b[pos + 1] << 8)
                     | b[pos + 2]) + 526336
            else:
                v = struct.unpack_from(">I", b, pos)[0]
            if self.data_start is None:
                raise MMDBError("pointer in metadata section")
            out, _ = self._decode(self.data_start + v)
            return out, pos + psize
        if dtype == 0:                   # extended type
            dtype = b[pos] + 7
            pos += 1
        size = ctrl & 0x1F
        if size == 29:
            size = 29 + b[pos]
            pos += 1
        elif size == 30:
            size = 285 + struct.unpack_from(">H", b, pos)[0]
            pos += 2
        elif size == 31:
            size = 65821 + int.from_bytes(b[pos : pos + 3], "big")
            pos += 3
        if dtype == 2:                   # utf8 string
            return b[pos : pos + size].decode("utf-8", "replace"), pos + size
        if dtype == 3:                   # double
            return struct.unpack_from(">d", b, pos)[0], pos + 8
        if dtype == 4:                   # bytes
            return b[pos : pos + size], pos + size
        if dtype in (5, 6, 9, 10):       # uint16/32/64/128
            return int.from_bytes(b[pos : pos + size], "big"), pos + size
        if dtype == 7:                   # map
            out = {}
            for _ in range(size):
                key, pos = self._decode(pos)
                val, pos = self._decode(pos)
                out[key] = val
            return out, pos
        if dtype == 8:                   # int32
            v = int.from_bytes(b[pos : pos + size], "big")
            if size and v >= 1 << (size * 8 - 1):
                v -= 1 << (size * 8)
            return v, pos + size
        if dtype == 11:                  # array
            out = []
            for _ in range(size):
                val, pos = self._decode(pos)
                out.append(val)
            return out, pos
        if dtype == 14:                  # boolean (size IS the value)
            return bool(size), pos
        if dtype == 15:                  # float
            return struct.unpack_from(">f", b, pos)[0], pos + 4
        raise MMDBError(f"unsupported data type {dtype}")
