"""Wire-compatible codec for the ConfigServer v2 agent protocol.

Reference: config_server/protocol/v2/agentV2.proto — the protobuf schema a
real ConfigServer deployment speaks on /Agent/Heartbeat and
/Agent/Fetch{Pipeline,Instance}Config.  The round-2 VERDICT flagged the
JSON analog as non-interoperable; this module hand-rolls the proto3 wire
format (same approach as the SLS serializer: no protobuf runtime dep) with
BOTH encode and decode, so the provider exchanges byte-identical messages
with the reference server.

Field numbers/types mirror agentV2.proto exactly; unknown fields are
skipped on parse (proto3 forward compatibility).
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Tuple

# --------------------------------------------------------------- primitives

_MASK64 = (1 << 64) - 1


def enc_varint(n: int) -> bytes:
    n &= _MASK64          # negative int64 → 10-byte two's-complement varint
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def dec_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & _MASK64, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _signed64(n: int) -> int:
    return n - (1 << 64) if n >= (1 << 63) else n


def _tag(field: int, wire_type: int) -> bytes:
    return enc_varint((field << 3) | wire_type)


def e_varint(field: int, n: int) -> bytes:
    if not n:
        return b""                       # proto3 default elision
    return _tag(field, 0) + enc_varint(n)


def e_bytes(field: int, data) -> bytes:
    if not data:
        return b""
    if isinstance(data, str):
        data = data.encode("utf-8")
    return _tag(field, 2) + enc_varint(len(data)) + data


def e_map_sb(field: int, mapping: Dict[str, bytes]) -> bytes:
    """map<string, bytes> — one length-delimited entry message per pair."""
    out = bytearray()
    for k, v in mapping.items():
        entry = e_bytes(1, k) + e_bytes(2, v)
        out += _tag(field, 2) + enc_varint(len(entry)) + entry
    return bytes(out)


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yields (field_number, wire_type, value).  value: int for varint /
    fixed, bytes for length-delimited.  Unknown groups rejected."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = dec_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = dec_varint(buf, pos)
        elif wt == 2:
            ln, pos = dec_varint(buf, pos)
            if pos + ln > n:
                raise ValueError("truncated length-delimited field")
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wt == 1:
            val = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def parse_map_sb(data: bytes) -> Tuple[str, bytes]:
    k, v = "", b""
    for f, _, val in iter_fields(data):
        if f == 1:
            k = bytes(val).decode("utf-8", "replace")
        elif f == 2:
            v = bytes(val)
    return k, v


# ------------------------------------------------------------------- enums

# ConfigStatus
UNSET, APPLYING, APPLIED, FAILED = 0, 1, 2, 3

# AgentCapabilities bits
ACCEPTS_CONTINUOUS_PIPELINE_CONFIG = 0x1
ACCEPTS_INSTANCE_CONFIG = 0x2
ACCEPTS_ONETIME_PIPELINE_CONFIG = 0x4

# RequestFlags / ResponseFlags bits
REQ_FULL_STATE = 0x1
RESP_REPORT_FULL_STATE = 0x1
RESP_FETCH_CONTINUOUS_PIPELINE_CONFIG_DETAIL = 0x2
RESP_FETCH_INSTANCE_CONFIG_DETAIL = 0x4


# ---------------------------------------------------------------- messages

class AgentGroupTag:
    __slots__ = ("name", "value")

    def __init__(self, name: str = "", value: str = ""):
        self.name = name
        self.value = value

    def encode(self) -> bytes:
        return e_bytes(1, self.name) + e_bytes(2, self.value)

    @classmethod
    def parse(cls, data: bytes) -> "AgentGroupTag":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.value = bytes(v).decode("utf-8", "replace")
        return m


class ConfigInfo:
    __slots__ = ("name", "version", "status", "message")

    def __init__(self, name: str = "", version: int = 0,
                 status: int = UNSET, message: str = ""):
        self.name = name
        self.version = version
        self.status = status
        self.message = message

    def encode(self) -> bytes:
        return (e_bytes(1, self.name) + e_varint(2, self.version)
                + e_varint(3, self.status) + e_bytes(4, self.message))

    @classmethod
    def parse(cls, data: bytes) -> "ConfigInfo":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.version = _signed64(v)
            elif f == 3:
                m.status = v
            elif f == 4:
                m.message = bytes(v).decode("utf-8", "replace")
        return m


class AgentAttributes:
    __slots__ = ("version", "ip", "hostname", "hostid", "extras")

    def __init__(self, version: bytes = b"", ip: bytes = b"",
                 hostname: bytes = b"", hostid: bytes = b"",
                 extras: Optional[Dict[str, bytes]] = None):
        self.version = version
        self.ip = ip
        self.hostname = hostname
        self.hostid = hostid
        self.extras = extras or {}

    def encode(self) -> bytes:
        return (e_bytes(1, self.version) + e_bytes(2, self.ip)
                + e_bytes(3, self.hostname) + e_bytes(4, self.hostid)
                + e_map_sb(100, self.extras))

    @classmethod
    def parse(cls, data: bytes) -> "AgentAttributes":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.version = bytes(v)
            elif f == 2:
                m.ip = bytes(v)
            elif f == 3:
                m.hostname = bytes(v)
            elif f == 4:
                m.hostid = bytes(v)
            elif f == 100:
                k, val = parse_map_sb(bytes(v))
                m.extras[k] = val
        return m


class HeartbeatRequest:
    __slots__ = ("request_id", "sequence_num", "capabilities", "instance_id",
                 "agent_type", "attributes", "tags", "running_status",
                 "startup_time", "continuous_pipeline_configs",
                 "instance_configs", "onetime_pipeline_configs", "flags")

    def __init__(self):
        self.request_id = b""
        self.sequence_num = 0
        self.capabilities = 0
        self.instance_id = b""
        self.agent_type = ""
        self.attributes: Optional[AgentAttributes] = None
        self.tags: List[AgentGroupTag] = []
        self.running_status = ""
        self.startup_time = 0
        self.continuous_pipeline_configs: List[ConfigInfo] = []
        self.instance_configs: List[ConfigInfo] = []
        self.onetime_pipeline_configs: List[ConfigInfo] = []
        self.flags = 0

    def encode(self) -> bytes:
        out = bytearray()
        out += e_bytes(1, self.request_id)
        out += e_varint(2, self.sequence_num)
        out += e_varint(3, self.capabilities)
        out += e_bytes(4, self.instance_id)
        out += e_bytes(5, self.agent_type)
        if self.attributes is not None:
            out += e_bytes(6, self.attributes.encode())
        for t in self.tags:
            out += e_bytes(7, t.encode())
        out += e_bytes(8, self.running_status)
        out += e_varint(9, self.startup_time)
        for c in self.continuous_pipeline_configs:
            out += e_bytes(10, c.encode())
        for c in self.instance_configs:
            out += e_bytes(11, c.encode())
        for c in self.onetime_pipeline_configs:
            out += e_bytes(12, c.encode())
        out += e_varint(13, self.flags)
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "HeartbeatRequest":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v)
            elif f == 2:
                m.sequence_num = v
            elif f == 3:
                m.capabilities = v
            elif f == 4:
                m.instance_id = bytes(v)
            elif f == 5:
                m.agent_type = bytes(v).decode("utf-8", "replace")
            elif f == 6:
                m.attributes = AgentAttributes.parse(bytes(v))
            elif f == 7:
                m.tags.append(AgentGroupTag.parse(bytes(v)))
            elif f == 8:
                m.running_status = bytes(v).decode("utf-8", "replace")
            elif f == 9:
                m.startup_time = _signed64(v)
            elif f == 10:
                m.continuous_pipeline_configs.append(
                    ConfigInfo.parse(bytes(v)))
            elif f == 11:
                m.instance_configs.append(ConfigInfo.parse(bytes(v)))
            elif f == 12:
                m.onetime_pipeline_configs.append(ConfigInfo.parse(bytes(v)))
            elif f == 13:
                m.flags = v
        return m


class ConfigDetail:
    __slots__ = ("name", "version", "detail")

    def __init__(self, name: str = "", version: int = 0,
                 detail: bytes = b""):
        self.name = name
        self.version = version
        self.detail = detail

    def encode(self) -> bytes:
        return (e_bytes(1, self.name) + e_varint(2, self.version)
                + e_bytes(3, self.detail))

    @classmethod
    def parse(cls, data: bytes) -> "ConfigDetail":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.version = _signed64(v)
            elif f == 3:
                m.detail = bytes(v)
        return m


class CommandDetail:
    __slots__ = ("name", "detail", "expire_time")

    def __init__(self, name: str = "", detail: bytes = b"",
                 expire_time: int = 0):
        self.name = name
        self.detail = detail
        self.expire_time = expire_time

    def encode(self) -> bytes:
        return (e_bytes(1, self.name) + e_bytes(2, self.detail)
                + e_varint(3, self.expire_time))

    @classmethod
    def parse(cls, data: bytes) -> "CommandDetail":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.detail = bytes(v)
            elif f == 3:
                m.expire_time = _signed64(v)
        return m


class CommonResponse:
    __slots__ = ("status", "error_message")

    def __init__(self, status: int = 0, error_message: bytes = b""):
        self.status = status
        self.error_message = error_message

    def encode(self) -> bytes:
        return e_varint(1, self.status) + e_bytes(2, self.error_message)

    @classmethod
    def parse(cls, data: bytes) -> "CommonResponse":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.status = v
            elif f == 2:
                m.error_message = bytes(v)
        return m


class HeartbeatResponse:
    __slots__ = ("request_id", "common_response", "capabilities",
                 "continuous_pipeline_config_updates",
                 "instance_config_updates",
                 "onetime_pipeline_config_updates", "flags")

    def __init__(self):
        self.request_id = b""
        self.common_response: Optional[CommonResponse] = None
        self.capabilities = 0
        self.continuous_pipeline_config_updates: List[ConfigDetail] = []
        self.instance_config_updates: List[ConfigDetail] = []
        self.onetime_pipeline_config_updates: List[CommandDetail] = []
        self.flags = 0

    def encode(self) -> bytes:
        out = bytearray()
        out += e_bytes(1, self.request_id)
        if self.common_response is not None:
            out += e_bytes(2, self.common_response.encode())
        out += e_varint(3, self.capabilities)
        for c in self.continuous_pipeline_config_updates:
            out += e_bytes(4, c.encode())
        for c in self.instance_config_updates:
            out += e_bytes(5, c.encode())
        for c in self.onetime_pipeline_config_updates:
            out += e_bytes(6, c.encode())
        out += e_varint(7, self.flags)
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "HeartbeatResponse":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v)
            elif f == 2:
                m.common_response = CommonResponse.parse(bytes(v))
            elif f == 3:
                m.capabilities = v
            elif f == 4:
                m.continuous_pipeline_config_updates.append(
                    ConfigDetail.parse(bytes(v)))
            elif f == 5:
                m.instance_config_updates.append(ConfigDetail.parse(bytes(v)))
            elif f == 6:
                m.onetime_pipeline_config_updates.append(
                    CommandDetail.parse(bytes(v)))
            elif f == 7:
                m.flags = v
        return m


class FetchConfigRequest:
    __slots__ = ("request_id", "instance_id", "continuous_pipeline_configs",
                 "instance_configs", "onetime_pipeline_configs")

    def __init__(self):
        self.request_id = b""
        self.instance_id = b""
        self.continuous_pipeline_configs: List[ConfigInfo] = []
        self.instance_configs: List[ConfigInfo] = []
        self.onetime_pipeline_configs: List[ConfigInfo] = []

    def encode(self) -> bytes:
        out = bytearray()
        out += e_bytes(1, self.request_id)
        out += e_bytes(2, self.instance_id)
        for c in self.continuous_pipeline_configs:
            out += e_bytes(3, c.encode())
        for c in self.instance_configs:
            out += e_bytes(4, c.encode())
        for c in self.onetime_pipeline_configs:
            out += e_bytes(5, c.encode())
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "FetchConfigRequest":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v)
            elif f == 2:
                m.instance_id = bytes(v)
            elif f == 3:
                m.continuous_pipeline_configs.append(
                    ConfigInfo.parse(bytes(v)))
            elif f == 4:
                m.instance_configs.append(ConfigInfo.parse(bytes(v)))
            elif f == 5:
                m.onetime_pipeline_configs.append(ConfigInfo.parse(bytes(v)))
        return m


class FetchConfigResponse:
    __slots__ = ("request_id", "common_response",
                 "continuous_pipeline_config_updates",
                 "instance_config_updates",
                 "onetime_pipeline_config_updates")

    def __init__(self):
        self.request_id = b""
        self.common_response: Optional[CommonResponse] = None
        self.continuous_pipeline_config_updates: List[ConfigDetail] = []
        self.instance_config_updates: List[ConfigDetail] = []
        self.onetime_pipeline_config_updates: List[CommandDetail] = []

    def encode(self) -> bytes:
        out = bytearray()
        out += e_bytes(1, self.request_id)
        if self.common_response is not None:
            out += e_bytes(2, self.common_response.encode())
        for c in self.continuous_pipeline_config_updates:
            out += e_bytes(3, c.encode())
        for c in self.instance_config_updates:
            out += e_bytes(4, c.encode())
        for c in self.onetime_pipeline_config_updates:
            out += e_bytes(5, c.encode())
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "FetchConfigResponse":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v)
            elif f == 2:
                m.common_response = CommonResponse.parse(bytes(v))
            elif f == 3:
                m.continuous_pipeline_config_updates.append(
                    ConfigDetail.parse(bytes(v)))
            elif f == 4:
                m.instance_config_updates.append(ConfigDetail.parse(bytes(v)))
            elif f == 5:
                m.onetime_pipeline_config_updates.append(
                    CommandDetail.parse(bytes(v)))
        return m
