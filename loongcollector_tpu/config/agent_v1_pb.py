"""Wire-compatible codec for the LEGACY ConfigServer v1 agent protocol.

Reference: config_server/protocol/v1/agent.proto — the protocol the first
ConfigServer generation speaks on /Agent/HeartBeat/ and
/Agent/FetchPipelineConfig/.  v2 deployments remain the default
(agent_v2_pb.py); this codec exists so agents can enrol against the older
control planes still in the field (VERDICT r4: v1 absent).

Same approach as the v2 codec: hand-rolled proto3 wire format, encode AND
decode, unknown fields skipped.  Primitives are imported from the v2 module
— one varint implementation, not two.
"""

from __future__ import annotations

from typing import Dict, List

from .agent_v2_pb import (e_bytes, e_map_sb, e_varint, iter_fields,
                          parse_map_sb, _signed64)

# enums (agent.proto)
PIPELINE_CONFIG = 0
AGENT_CONFIG = 1

CHECK_NEW = 0
CHECK_DELETED = 1
CHECK_MODIFIED = 2

RESP_ACCEPT = 0
RESP_INVALID_PARAMETER = 1
RESP_INTERNAL_SERVER_ERROR = 2


class ConfigInfoV1:
    __slots__ = ("type", "name", "version", "context")

    def __init__(self, name: str = "", version: int = 0,
                 type: int = PIPELINE_CONFIG, context: str = ""):
        self.type = type
        self.name = name
        self.version = version
        self.context = context

    def encode(self) -> bytes:
        return (e_varint(1, self.type) + e_bytes(2, self.name)
                + e_varint(3, self.version) + e_bytes(4, self.context))

    @classmethod
    def parse(cls, data: bytes) -> "ConfigInfoV1":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.type = v
            elif f == 2:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.version = _signed64(v)
            elif f == 4:
                m.context = bytes(v).decode("utf-8", "replace")
        return m


class ConfigCheckResult:
    __slots__ = ("type", "name", "old_version", "new_version", "context",
                 "check_status")

    def __init__(self) -> None:
        self.type = PIPELINE_CONFIG
        self.name = ""
        self.old_version = 0
        self.new_version = 0
        self.context = ""
        self.check_status = CHECK_NEW

    def encode(self) -> bytes:
        return (e_varint(1, self.type) + e_bytes(2, self.name)
                + e_varint(3, self.old_version)
                + e_varint(4, self.new_version) + e_bytes(5, self.context)
                + e_varint(6, self.check_status))

    @classmethod
    def parse(cls, data: bytes) -> "ConfigCheckResult":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.type = v
            elif f == 2:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.old_version = _signed64(v)
            elif f == 4:
                m.new_version = _signed64(v)
            elif f == 5:
                m.context = bytes(v).decode("utf-8", "replace")
            elif f == 6:
                m.check_status = v
        return m


class ConfigDetailV1:
    __slots__ = ("type", "name", "version", "context", "detail")

    def __init__(self, name: str = "", version: int = 0, detail: str = "",
                 type: int = PIPELINE_CONFIG, context: str = ""):
        self.type = type
        self.name = name
        self.version = version
        self.context = context
        self.detail = detail

    def encode(self) -> bytes:
        return (e_varint(1, self.type) + e_bytes(2, self.name)
                + e_varint(3, self.version) + e_bytes(4, self.context)
                + e_bytes(5, self.detail))

    @classmethod
    def parse(cls, data: bytes) -> "ConfigDetailV1":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.type = v
            elif f == 2:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.version = _signed64(v)
            elif f == 4:
                m.context = bytes(v).decode("utf-8", "replace")
            elif f == 5:
                m.detail = bytes(v).decode("utf-8", "replace")
        return m


class AgentAttributesV1:
    __slots__ = ("version", "category", "ip", "hostname", "region", "zone",
                 "extras")

    def __init__(self) -> None:
        self.version = ""
        self.category = ""
        self.ip = ""
        self.hostname = ""
        self.region = ""
        self.zone = ""
        self.extras: Dict[str, str] = {}

    def encode(self) -> bytes:
        return (e_bytes(1, self.version) + e_bytes(2, self.category)
                + e_bytes(3, self.ip) + e_bytes(4, self.hostname)
                + e_bytes(5, self.region) + e_bytes(6, self.zone)
                + e_map_sb(100, self.extras))

    @classmethod
    def parse(cls, data: bytes) -> "AgentAttributesV1":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.version = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.category = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.ip = bytes(v).decode("utf-8", "replace")
            elif f == 4:
                m.hostname = bytes(v).decode("utf-8", "replace")
            elif f == 5:
                m.region = bytes(v).decode("utf-8", "replace")
            elif f == 6:
                m.zone = bytes(v).decode("utf-8", "replace")
            elif f == 100:
                k, val = parse_map_sb(bytes(v))
                m.extras[k] = val.decode("utf-8", "replace")
        return m


class Command:
    __slots__ = ("type", "name", "id", "args")

    def __init__(self) -> None:
        self.type = ""
        self.name = ""
        self.id = ""
        self.args: Dict[str, str] = {}

    def encode(self) -> bytes:
        return (e_bytes(1, self.type) + e_bytes(2, self.name)
                + e_bytes(3, self.id) + e_map_sb(4, self.args))

    @classmethod
    def parse(cls, data: bytes) -> "Command":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.type = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.name = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.id = bytes(v).decode("utf-8", "replace")
            elif f == 4:
                k, val = parse_map_sb(bytes(v))
                m.args[k] = val.decode("utf-8", "replace")
        return m


class HeartBeatRequestV1:
    __slots__ = ("request_id", "agent_id", "agent_type", "attributes",
                 "tags", "running_status", "startup_time", "interval",
                 "pipeline_configs", "agent_configs")

    def __init__(self) -> None:
        self.request_id = ""
        self.agent_id = ""
        self.agent_type = "loongcollector-tpu"
        self.attributes = AgentAttributesV1()
        self.tags: List[str] = []
        self.running_status = "running"
        self.startup_time = 0
        self.interval = 10
        self.pipeline_configs: List[ConfigInfoV1] = []
        self.agent_configs: List[ConfigInfoV1] = []

    def encode(self) -> bytes:
        out = (e_bytes(1, self.request_id) + e_bytes(2, self.agent_id)
               + e_bytes(3, self.agent_type)
               + e_bytes(4, self.attributes.encode()))
        for t in self.tags:
            out += e_bytes(5, t)
        out += (e_bytes(6, self.running_status)
                + e_varint(7, self.startup_time)
                + e_varint(8, self.interval))
        for c in self.pipeline_configs:
            out += e_bytes(9, c.encode())
        for c in self.agent_configs:
            out += e_bytes(10, c.encode())
        return out

    @classmethod
    def parse(cls, data: bytes) -> "HeartBeatRequestV1":
        m = cls()
        m.tags, m.pipeline_configs, m.agent_configs = [], [], []
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.agent_id = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.agent_type = bytes(v).decode("utf-8", "replace")
            elif f == 4:
                m.attributes = AgentAttributesV1.parse(bytes(v))
            elif f == 5:
                m.tags.append(bytes(v).decode("utf-8", "replace"))
            elif f == 6:
                m.running_status = bytes(v).decode("utf-8", "replace")
            elif f == 7:
                m.startup_time = _signed64(v)
            elif f == 8:
                m.interval = _signed64(v)
            elif f == 9:
                m.pipeline_configs.append(ConfigInfoV1.parse(bytes(v)))
            elif f == 10:
                m.agent_configs.append(ConfigInfoV1.parse(bytes(v)))
        return m


class HeartBeatResponseV1:
    __slots__ = ("request_id", "code", "message", "pipeline_check_results",
                 "agent_check_results", "custom_commands")

    def __init__(self) -> None:
        self.request_id = ""
        self.code = RESP_ACCEPT
        self.message = ""
        self.pipeline_check_results: List[ConfigCheckResult] = []
        self.agent_check_results: List[ConfigCheckResult] = []
        self.custom_commands: List[Command] = []

    def encode(self) -> bytes:
        out = (e_bytes(1, self.request_id) + e_varint(2, self.code)
               + e_bytes(3, self.message))
        for r in self.pipeline_check_results:
            out += e_bytes(4, r.encode())
        for r in self.agent_check_results:
            out += e_bytes(5, r.encode())
        for c in self.custom_commands:
            out += e_bytes(6, c.encode())
        return out

    @classmethod
    def parse(cls, data: bytes) -> "HeartBeatResponseV1":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.code = v
            elif f == 3:
                m.message = bytes(v).decode("utf-8", "replace")
            elif f == 4:
                m.pipeline_check_results.append(
                    ConfigCheckResult.parse(bytes(v)))
            elif f == 5:
                m.agent_check_results.append(
                    ConfigCheckResult.parse(bytes(v)))
            elif f == 6:
                m.custom_commands.append(Command.parse(bytes(v)))
        return m


class FetchPipelineConfigRequestV1:
    __slots__ = ("request_id", "agent_id", "req_configs")

    def __init__(self) -> None:
        self.request_id = ""
        self.agent_id = ""
        self.req_configs: List[ConfigInfoV1] = []

    def encode(self) -> bytes:
        out = e_bytes(1, self.request_id) + e_bytes(2, self.agent_id)
        for c in self.req_configs:
            out += e_bytes(3, c.encode())
        return out

    @classmethod
    def parse(cls, data: bytes) -> "FetchPipelineConfigRequestV1":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.agent_id = bytes(v).decode("utf-8", "replace")
            elif f == 3:
                m.req_configs.append(ConfigInfoV1.parse(bytes(v)))
        return m


class FetchPipelineConfigResponseV1:
    __slots__ = ("request_id", "code", "message", "config_details")

    def __init__(self) -> None:
        self.request_id = ""
        self.code = RESP_ACCEPT
        self.message = ""
        self.config_details: List[ConfigDetailV1] = []

    def encode(self) -> bytes:
        out = (e_bytes(1, self.request_id) + e_varint(2, self.code)
               + e_bytes(3, self.message))
        for d in self.config_details:
            out += e_bytes(4, d.encode())
        return out

    @classmethod
    def parse(cls, data: bytes) -> "FetchPipelineConfigResponseV1":
        m = cls()
        for f, _, v in iter_fields(data):
            if f == 1:
                m.request_id = bytes(v).decode("utf-8", "replace")
            elif f == 2:
                m.code = v
            elif f == 3:
                m.message = bytes(v).decode("utf-8", "replace")
            elif f == 4:
                m.config_details.append(ConfigDetailV1.parse(bytes(v)))
        return m
