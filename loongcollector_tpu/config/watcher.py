"""Pipeline config directory watcher.

Reference: core/config/watcher/PipelineConfigWatcher.cpp — scans watched
directories every poll round, diffs by mtime+size, and emits a ConfigDiff
{added, modified, removed} that the pipeline manager applies atomically
(application/Application.cpp:323-331).

Config files: one pipeline per YAML or JSON file; the stem is the pipeline
name.  YAML is parsed when PyYAML exists (baked in transformers deps),
JSON always.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..pipeline.pipeline_manager import ConfigDiff
from ..utils.logger import get_logger

log = get_logger("config_watcher")

try:
    import yaml as _yaml
except ImportError:  # pragma: no cover
    _yaml = None


def _expand_env(text: str) -> str:
    """${NAME} → environment value (credentials stay out of config files,
    reference AppConfig env-override shape).  Unset names stay literal so
    a typo is visible instead of silently becoming empty."""
    import os
    import re

    def sub(m):
        return os.environ.get(m.group(1), m.group(0))

    return re.sub(r"\$\{(\w+)\}", sub, text)


def load_config_file(path: str) -> Optional[dict]:
    cfg, _digest = load_config_file_hashed(path)
    return cfg


def load_config_file_hashed(path: str) -> Tuple[Optional[dict], str]:
    """(config, content digest).  The digest is over the env-EXPANDED
    text — the content the pipeline would actually run — so the watcher
    can tell an unchanged-content rewrite (mtime bumped, same effective
    config) from a real edit, while a credential rotation (same file
    bytes, ``${TOKEN}`` now expanding differently) still re-applies when
    the file is re-pushed.  Returns (None, "") on read failure,
    (None, digest) on a parse failure — the caller keeps the previous
    generation either way."""
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return None, ""
    text = _expand_env(raw)
    digest = hashlib.sha256(
        text.encode("utf-8", "surrogatepass")).hexdigest()
    return _parse_config_text(path, text), digest


def _parse_config_text(path: str, text: str) -> Optional[dict]:
    if path.endswith((".yaml", ".yml")):
        if _yaml is None:
            log.error("PyYAML unavailable; cannot load %s", path)
            _config_alarm(path, "PyYAML unavailable")
            return None
        try:
            return _yaml.safe_load(text)
        except _yaml.YAMLError as e:
            log.error("bad yaml %s: %s", path, e)
            _config_alarm(path, e)
            return None
    try:
        return json.loads(text)
    except ValueError as e:
        log.error("bad json %s: %s", path, e)
        _config_alarm(path, e)
        return None


def _config_alarm(path: str, err) -> None:
    AlarmManager.instance().send_alarm(
        AlarmType.USER_CONFIG, f"unparsable config {path}: {err}",
        AlarmLevel.ERROR)


# Built-in pipelines (reference PipelineConfigWatcher::InsertBuiltInPipelines
# — enterprise builds inject provider-supplied configs ahead of the file
# scan; the open equivalent is this registry).  Builtins win name clashes
# with file configs, exactly like the reference's configSet ordering.
_builtin_pipelines: Dict[str, Tuple[int, dict]] = {}  # name -> (epoch, cfg)
_builtin_epoch = 0


def register_builtin_pipeline(name: str, config: dict) -> None:
    """Ship a pipeline with the agent itself (no config file on disk).
    Registered before the watcher's next scan; same-name file configs are
    shadowed.  A monotonic epoch (not object identity) detects
    re-registration, so replace-with-same-address or in-place edits after
    re-register still roll out."""
    global _builtin_epoch
    _builtin_epoch += 1
    _builtin_pipelines[name] = (_builtin_epoch, config)


def unregister_builtin_pipeline(name: str) -> None:
    global _builtin_epoch
    if _builtin_pipelines.pop(name, None) is not None:
        _builtin_epoch += 1


class PipelineConfigWatcher:
    def __init__(self) -> None:
        self._dirs: List[str] = []
        # path -> ((mtime, size), content sha256) of the last APPLIED
        # version; a malformed rewrite deliberately leaves the old entry
        # (the previous generation keeps serving, the scan retries)
        self._state: Dict[str, Tuple[Tuple[float, int], str]] = {}
        # name -> path the name was last applied from: lets one scan
        # classify remove+re-add (the config moved files, e.g. .yaml →
        # .json) as a MODIFY, so the pipeline keeps its queue key and its
        # queued groups survive the swap
        self._names: Dict[str, str] = {}
        self._builtin_applied: Dict[str, int] = {}  # name -> id(config)

    def add_source(self, directory: str) -> None:
        if directory not in self._dirs:
            self._dirs.append(directory)

    def check_config_diff(self) -> ConfigDiff:
        diff = ConfigDiff()
        seen: Dict[str, str] = {}  # name -> path
        # builtins first: they claim their names before the file scan
        for name, (epoch, cfg) in _builtin_pipelines.items():
            seen[name] = f"builtin://{name}"
            # forget any shadowed file's scan state so the file re-applies
            # the moment the builtin unregisters (an unchanged mtime/size
            # signature would otherwise suppress its re-discovery forever)
            for path in list(self._state):
                if os.path.splitext(os.path.basename(path))[0] == name:
                    del self._state[path]
            if self._builtin_applied.get(name) != epoch:
                if name in self._builtin_applied:
                    diff.modified[name] = cfg
                else:
                    diff.added[name] = cfg
                self._builtin_applied[name] = epoch
        for name in list(self._builtin_applied):
            if name not in _builtin_pipelines:
                del self._builtin_applied[name]
                diff.removed.append(name)
        for d in self._dirs:
            if not os.path.isdir(d):
                continue
            for fn in sorted(os.listdir(d)):
                if not fn.endswith((".json", ".yaml", ".yml")):
                    continue
                path = os.path.join(d, fn)
                name = os.path.splitext(fn)[0]
                if name in seen:
                    continue
                seen[name] = path
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                sig = (st.st_mtime, st.st_size)
                old = self._state.get(path)
                if old is not None and old[0] == sig:
                    self._names.setdefault(name, path)
                    continue
                cfg, digest = load_config_file_hashed(path)
                if cfg is None:
                    # unreadable or MALFORMED: the previous generation
                    # keeps serving — state is NOT updated, so a later
                    # scan retries (and a fixed file applies normally);
                    # never a removal, never a half-applied modify
                    continue
                prev_path = self._names.get(name)
                known = (old is not None
                         or (prev_path is not None and prev_path != path))
                if old is not None and old[1] == digest:
                    # unchanged-content rewrite (touch, atomic re-write
                    # with identical bytes): refresh the signature but do
                    # NOT restart the pipeline over a no-op edit
                    self._state[path] = (sig, digest)
                    continue
                self._state[path] = (sig, digest)
                if prev_path is not None and prev_path != path:
                    # the name moved files (remove + re-add seen in ONE
                    # scan): a modify — the manager reuses the queue key
                    self._state.pop(prev_path, None)
                self._names[name] = path
                if known:
                    diff.modified[name] = cfg
                else:
                    diff.added[name] = cfg
        # removals: tracked paths whose file vanished
        for path in list(self._state):
            if not os.path.exists(path):
                del self._state[path]
                name = os.path.splitext(os.path.basename(path))[0]
                if name not in seen:
                    diff.removed.append(name)
                    if self._names.get(name) == path:
                        del self._names[name]
        return diff
