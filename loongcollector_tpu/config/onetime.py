"""Onetime config lifecycle.

Reference: core/config/OnetimeConfigInfoManager.cpp + Application.cpp:309-321
— one-shot jobs (static file imports) are tracked by config content hash
with an expiry; finished/expired configs are not re-run on restart and are
eventually dropped.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, Optional

DEFAULT_TTL_S = 24 * 3600.0


def config_hash(config: dict) -> str:
    return hashlib.sha256(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:16]


class OnetimeConfigInfoManager:
    def __init__(self, state_path: str = "", ttl_s: float = DEFAULT_TTL_S):
        self.state_path = state_path
        self.ttl_s = ttl_s
        self._done: Dict[str, float] = {}  # hash -> completion time
        self._lock = threading.Lock()

    def load(self) -> None:
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path) as f:
                self._done = {k: float(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            self._done = {}

    def dump(self) -> None:
        if not self.state_path:
            return
        with self._lock:
            data = dict(self._done)
        tmp = self.state_path + ".tmp"
        os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, self.state_path)

    def already_ran(self, config: dict) -> bool:
        h = config_hash(config)
        with self._lock:
            return h in self._done

    def mark_done(self, config: dict) -> None:
        with self._lock:
            self._done[config_hash(config)] = time.time()
        self.dump()

    def gc_expired(self) -> int:
        """Drops completion records older than the TTL.  NOT called at
        startup: a record must outlive any copy of its config file on disk,
        or a restart would re-run the import (duplicate data).  Intended for
        explicit cleanup once the config files themselves are gone."""
        cutoff = time.time() - self.ttl_s
        with self._lock:
            stale = [h for h, t in self._done.items() if t < cutoff]
            for h in stale:
                del self._done[h]
        if stale:
            self.dump()
        return len(stale)
