"""Legacy (v1) ConfigServer provider.

Reference: config_server/protocol/v1/agent.proto + the v1 enrolment flow —
HeartBeat carries the held (name, version) set; the server answers with
per-config check results (NEW / MODIFIED / DELETED); details for changed
configs are pulled via /Agent/FetchPipelineConfig/.

Shares everything operational with the v2 provider (scheduling, backoff,
config-dir materialization, safe-name policy) and swaps only the wire
protocol, so `config_server_protocol: v1` in the agent config is the whole
migration story for fleets still on the first-generation control plane.
"""

from __future__ import annotations

import time
import uuid
from typing import List

from . import agent_v1_pb as pb1
from .common_provider import AGENT_VERSION, CommonConfigProvider
from ..utils.logger import get_logger

log = get_logger("config_provider_v1")


class _DetailShim:
    """Adapter: v1 fetch results / delete sentinels in the shape
    _apply_updates consumes (name, version, detail bytes)."""

    __slots__ = ("name", "version", "detail")

    def __init__(self, name: str, version: int, detail: bytes):
        self.name = name
        self.version = version
        self.detail = detail


class LegacyConfigProvider(CommonConfigProvider):
    """v1-protocol ConfigServer client."""

    def _heartbeat_request_v1(self) -> pb1.HeartBeatRequestV1:
        req = pb1.HeartBeatRequestV1()
        req.request_id = uuid.uuid4().hex
        req.agent_id = self.instance_id
        req.agent_type = self.agent_type
        req.running_status = "running"
        req.startup_time = self.startup_time
        req.interval = int(self.interval_s)
        attrs = pb1.AgentAttributesV1()
        attrs.version = AGENT_VERSION
        attrs.hostname = self._hostname.decode("utf-8", "replace")
        attrs.ip = self._host_ip.decode("utf-8", "replace")
        req.attributes = attrs
        with self._lock:
            versions = dict(self._versions)
        req.pipeline_configs = [
            pb1.ConfigInfoV1(name=n, version=v) for n, v in versions.items()]
        return req

    def heartbeat_once(self) -> bool:
        body = self._post("/Agent/HeartBeat/",
                          self._heartbeat_request_v1().encode())
        if body is None:
            return False
        try:
            resp = pb1.HeartBeatResponseV1.parse(body)
        except ValueError:
            log.warning("undecodable v1 heartbeat response (%d bytes)",
                        len(body))
            return False
        if resp.code != pb1.RESP_ACCEPT:
            log.warning("v1 heartbeat rejected: %s %s", resp.code,
                        resp.message)
            return False
        updates: List[_DetailShim] = []
        to_fetch: List[pb1.ConfigInfoV1] = []
        for r in resp.pipeline_check_results:
            if r.check_status == pb1.CHECK_DELETED:
                updates.append(_DetailShim(r.name, -1, b""))
            else:  # NEW / MODIFIED
                to_fetch.append(
                    pb1.ConfigInfoV1(name=r.name, version=r.new_version))
        if to_fetch:
            updates.extend(self._fetch_details_v1(to_fetch))
        self._apply_updates(updates)
        return True

    def _fetch_details_v1(self, infos) -> List[_DetailShim]:
        req = pb1.FetchPipelineConfigRequestV1()
        req.request_id = uuid.uuid4().hex
        req.agent_id = self.instance_id
        req.req_configs = list(infos)
        body = self._post("/Agent/FetchPipelineConfig/", req.encode())
        if body is None:
            return []
        try:
            resp = pb1.FetchPipelineConfigResponseV1.parse(body)
        except ValueError:
            return []
        if resp.code != pb1.RESP_ACCEPT:
            log.warning("v1 fetch rejected: %s %s", resp.code, resp.message)
            return []
        return [_DetailShim(d.name, d.version, d.detail.encode())
                for d in resp.config_details]
