from .watcher import PipelineConfigWatcher
