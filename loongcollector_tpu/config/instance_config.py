"""Instance configs: agent-level settings applied WITHOUT pipeline restarts.

Reference: core/config/watcher/InstanceConfigWatcher.cpp (directory diff
over instance-config files, same mtime/size change detection as the
pipeline watcher) + core/config/InstanceConfigManager.cpp
(UpdateInstanceConfigs applies added/modified/removed configs to the
process-wide AppConfig without touching running pipelines).

An instance config file is a JSON/YAML map of flag overrides, e.g.
    {"config": {"cpu_usage_limit": 0.6, "max_bytes_per_sec": 1048576}}
(the flat form without the "config" wrapper is accepted too).  Multiple
configs merge in file-name order (later wins); removing a file reverts its
keys to the DEFAULT (or to the value from a remaining config) — applied
live through utils.flags set_flag, whose on_flag_change callbacks update
running components in place.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..utils import flags
from ..utils.logger import get_logger
from .watcher import load_config_file

log = get_logger("instance_config")


class InstanceConfigDiff:
    def __init__(self) -> None:
        self.added: Dict[str, dict] = {}
        self.modified: Dict[str, dict] = {}
        self.removed: List[str] = []

    def empty(self) -> bool:
        return not (self.added or self.modified or self.removed)


class InstanceConfigWatcher:
    """Directory diff for instance configs (mtime+size change detection,
    like PipelineConfigWatcher but feeding the flag layer)."""

    def __init__(self) -> None:
        self._dirs: List[str] = []
        self._state: Dict[str, Tuple[float, int]] = {}

    def add_source(self, directory: str) -> None:
        if directory not in self._dirs:
            self._dirs.append(directory)

    def check_config_diff(self) -> InstanceConfigDiff:
        diff = InstanceConfigDiff()
        seen: Dict[str, str] = {}
        for d in self._dirs:
            if not os.path.isdir(d):
                continue
            for fn in sorted(os.listdir(d)):
                if not fn.endswith((".json", ".yaml", ".yml")):
                    continue
                path = os.path.join(d, fn)
                name = os.path.splitext(fn)[0]
                if name in seen:
                    continue
                seen[name] = path
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                sig = (st.st_mtime, st.st_size)
                old = self._state.get(path)
                if old == sig:
                    continue
                cfg = load_config_file(path)
                if cfg is None:
                    continue
                self._state[path] = sig
                if old is None:
                    diff.added[name] = cfg
                else:
                    diff.modified[name] = cfg
        for path in list(self._state):
            if not os.path.exists(path):
                del self._state[path]
                name = os.path.splitext(os.path.basename(path))[0]
                if name not in seen:
                    diff.removed.append(name)
        return diff


class InstanceConfigManager:
    """Applies instance-config diffs to the flag layer, live.

    Keeps per-config key sets so removal reverts exactly the keys that
    config contributed; pipelines are never restarted (the point of
    instance configs — reference InstanceConfigManager.cpp)."""

    _instance: Optional["InstanceConfigManager"] = None

    def __init__(self) -> None:
        self._configs: Dict[str, Dict[str, object]] = {}
        self._defaults: Dict[str, object] = {}

    @classmethod
    def instance(cls) -> "InstanceConfigManager":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    @staticmethod
    def _flag_map(cfg: dict) -> Dict[str, object]:
        body = cfg.get("config", cfg)
        if not isinstance(body, dict):
            return {}
        return {str(k): v for k, v in body.items()}

    def update(self, diff: InstanceConfigDiff) -> None:
        if diff.empty():
            return
        for name in diff.removed:
            self._configs.pop(name, None)
        for name, cfg in list(diff.added.items()) + \
                list(diff.modified.items()):
            fm = self._flag_map(cfg)
            unknown = [k for k in fm if not flags.has_flag(k)]
            for k in unknown:
                log.warning("instance config %s: unknown flag %r ignored",
                            name, k)
                fm.pop(k)
            self._configs[name] = fm
            log.info("instance config %s applied: %s", name, fm)
        for name in diff.removed:
            log.info("instance config %s removed", name)
        self._apply()

    def find_config(self, name: str) -> Optional[Dict[str, object]]:
        return self._configs.get(name)

    def _apply(self) -> None:
        # snapshot defaults lazily the first time a key is overridden so
        # removal can restore them
        desired: Dict[str, object] = {}
        for name in sorted(self._configs):          # file-name order
            desired.update(self._configs[name])
        for key, value in desired.items():
            if key not in self._defaults:
                self._defaults[key] = flags.get_flag(key)
            try:
                flags.set_flag(key, value)
            except Exception:  # noqa: BLE001 — one bad value must not
                log.exception("instance config: set %s=%r failed", key, value)
        for key, default in list(self._defaults.items()):
            if key not in desired:
                try:
                    flags.set_flag(key, default)
                except Exception:  # noqa: BLE001 — a failing on_flag_change
                    # callback must not kill the application control loop
                    log.exception("instance config: restore %s=%r failed",
                                  key, default)
                del self._defaults[key]
