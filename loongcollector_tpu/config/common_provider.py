"""Remote config provider: agent ↔ ConfigServer heartbeat protocol.

Reference: core/config/common_provider/CommonConfigProvider.{h,cpp}
(h:57-78) + config_server/protocol/v2 — periodic Heartbeat carrying
capabilities + running status, response carries pipeline/instance config
updates which are materialised into the watched config directory; apply
status feeds back via ConfigFeedbackReceiver.

Transport: HTTP POST with the v2 message shapes as JSON (field-compatible
with the reference's protobuf schema: request_id, sequence_num, capabilities,
instance_id, agent_type, startup_time, pipeline_configs[{name, version,
detail}], ...).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Optional
from urllib.parse import urlparse

from ..utils.logger import get_logger


def _safe_name(name: str) -> bool:
    """Remote config names become file names — reject separators/traversal."""
    return bool(name) and "/" not in name and "\\" not in name \
        and ".." not in name and not name.startswith(".")

log = get_logger("config_provider")

# capability bits (reference config_server/protocol/v2 AgentCapabilities)
CAPA_ACCEPTS_PIPELINE_CONFIG = 1
CAPA_ACCEPTS_INSTANCE_CONFIG = 2
CAPA_REPORTS_FULL_STATE = 4


class CommonConfigProvider:
    def __init__(self, endpoint: str, config_dir: str,
                 interval_s: float = 10.0, agent_type: str = "loongcollector-tpu"):
        self.endpoint = endpoint
        self.config_dir = config_dir
        self.interval_s = interval_s
        self.agent_type = agent_type
        self.instance_id = str(uuid.uuid4())
        self.startup_time = int(time.time())
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # name -> version we currently hold
        self._versions: Dict[str, int] = {}
        # name -> (status, message) pending feedback
        self._feedback: Dict[str, tuple] = {}
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        os.makedirs(self.config_dir, exist_ok=True)
        self._thread = threading.Thread(target=self._run, name="config-provider",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=3)
            self._thread = None

    def feedback(self, config_name: str, status: str, message: str = "") -> None:
        """ConfigFeedbackReceiver: apply status reported on next heartbeat."""
        with self._lock:
            self._feedback[config_name] = (status, message)

    # -- protocol -----------------------------------------------------------

    def _heartbeat_request(self) -> dict:
        self._seq += 1
        with self._lock:
            feedback = [{"name": n, "status": s, "message": m}
                        for n, (s, m) in self._feedback.items()]
            self._feedback.clear()
            versions = [{"name": n, "version": v}
                        for n, v in self._versions.items()]
        return {
            "request_id": str(uuid.uuid4()),
            "sequence_num": self._seq,
            "capabilities": (CAPA_ACCEPTS_PIPELINE_CONFIG
                             | CAPA_REPORTS_FULL_STATE),
            "instance_id": self.instance_id,
            "agent_type": self.agent_type,
            "startup_time": self.startup_time,
            "running_status": "running",
            "pipeline_configs": versions,
            "config_feedback": feedback,
        }

    def _run(self) -> None:
        while self._running:
            try:
                self.heartbeat_once()
            except Exception:  # noqa: BLE001
                log.exception("heartbeat failed")
            for _ in range(int(self.interval_s * 10)):
                if not self._running:
                    return
                time.sleep(0.1)

    def heartbeat_once(self) -> bool:
        resp = self._post("/v2/Agent/Heartbeat", self._heartbeat_request())
        if resp is None:
            return False
        self._apply_response(resp)
        return True

    def _apply_response(self, resp: dict) -> None:
        for cfg in resp.get("pipeline_config_updates", []):
            name = cfg.get("name")
            version = int(cfg.get("version", 1))
            detail = cfg.get("detail")
            if not name or detail is None:
                continue
            if not _safe_name(name):
                log.warning("rejecting unsafe remote config name %r", name)
                continue
            if self._versions.get(name) == version:
                continue
            path = os.path.join(self.config_dir, f"{name}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                if isinstance(detail, str):
                    f.write(detail)
                else:
                    json.dump(detail, f)
            os.replace(tmp, path)
            with self._lock:
                self._versions[name] = version
            log.info("materialized remote config %s v%d", name, version)
        for name in resp.get("removed_configs", []):
            if not _safe_name(name):
                log.warning("rejecting unsafe remote config name %r", name)
                continue
            path = os.path.join(self.config_dir, f"{name}.json")
            if os.path.exists(path):
                os.remove(path)
            with self._lock:
                self._versions.pop(name, None)
            log.info("removed remote config %s", name)

    def _post(self, path: str, payload: dict) -> Optional[dict]:
        conn = None
        try:
            u = urlparse(self.endpoint)
            conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                        else http.client.HTTPConnection)
            conn = conn_cls(u.netloc, timeout=10)
            conn.request("POST", path, body=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            return json.loads(body)
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            if conn is not None:
                conn.close()
