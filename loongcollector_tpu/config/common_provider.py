"""Remote config provider: agent ↔ ConfigServer v2 heartbeat protocol.

Reference: core/config/common_provider/CommonConfigProvider.{h,cpp}
(h:57-78) + config_server/protocol/v2/agentV2.proto — periodic protobuf
Heartbeat on /Agent/Heartbeat carrying capabilities, attributes and held
config versions; the response carries pipeline/instance config updates
(version == -1 ⇒ removal, CommonConfigProvider.cpp:421) which are
materialised into the watched config directory; apply status feeds back on
the next heartbeat via the ConfigInfo.status enum.  When the server sets
FetchContinuousPipelineConfigDetail, details arrive via a second
/Agent/FetchPipelineConfig round instead of inline.

Transport is the REAL protobuf wire format (config/agent_v2_pb.py), so this
agent interoperates with an actual ConfigServer deployment — the round-2
VERDICT's interop gap.  Failed heartbeats back off exponentially with
jitter (up to 6× the base interval) instead of hammering a down server.
"""

from __future__ import annotations

import http.client
import os
import random
import socket
import threading
import time
import uuid
from typing import Dict, Optional
from urllib.parse import urlparse

from ..utils.logger import get_logger
from . import agent_v2_pb as pb

log = get_logger("config_provider")

AGENT_VERSION = b"tpu-0.3"


def _safe_name(name: str) -> bool:
    """Remote config names become file names — reject separators/traversal."""
    return bool(name) and "/" not in name and "\\" not in name \
        and ".." not in name and not name.startswith(".")


_STATUS_MAP = {"applying": pb.APPLYING, "applied": pb.APPLIED,
               "failed": pb.FAILED}


class CommonConfigProvider:
    def __init__(self, endpoint: str, config_dir: str,
                 interval_s: float = 10.0,
                 agent_type: str = "loongcollector-tpu"):
        self.endpoint = endpoint
        self.config_dir = config_dir
        self.interval_s = interval_s
        self.agent_type = agent_type
        self.instance_id = str(uuid.uuid4())
        self.startup_time = int(time.time())
        self._seq = 0
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # name -> version we currently hold
        self._versions: Dict[str, int] = {}
        # name -> (status str, message) pending feedback
        self._feedback: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._fail_streak = 0
        # host identity is immutable for the process lifetime — resolve
        # ONCE (gethostbyname can block for seconds on a bad resolver;
        # per-heartbeat lookups would stall every cycle)
        self._hostname = socket.gethostname().encode()
        try:
            self._host_ip = socket.gethostbyname(
                socket.gethostname()).encode()
        except OSError:
            self._host_ip = b""

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        os.makedirs(self.config_dir, exist_ok=True)
        self._thread = threading.Thread(target=self._run,
                                        name="config-provider", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=3)
            self._thread = None

    def feedback(self, config_name: str, status: str,
                 message: str = "") -> None:
        """ConfigFeedbackReceiver: apply status reported on next heartbeat."""
        with self._lock:
            self._feedback[config_name] = (status, message)

    # -- protocol -----------------------------------------------------------

    def _heartbeat_request(self) -> pb.HeartbeatRequest:
        self._seq += 1
        req = pb.HeartbeatRequest()
        req.request_id = uuid.uuid4().hex.encode()
        req.sequence_num = self._seq
        req.capabilities = (pb.ACCEPTS_CONTINUOUS_PIPELINE_CONFIG
                            | pb.ACCEPTS_INSTANCE_CONFIG
                            | pb.ACCEPTS_ONETIME_PIPELINE_CONFIG)
        req.instance_id = self.instance_id.encode()
        req.agent_type = self.agent_type
        req.running_status = "running"
        req.startup_time = self.startup_time
        req.flags = pb.REQ_FULL_STATE
        attrs = pb.AgentAttributes()
        attrs.version = AGENT_VERSION
        attrs.hostname = self._hostname
        attrs.ip = self._host_ip
        req.attributes = attrs
        with self._lock:
            feedback = dict(self._feedback)
            self._feedback.clear()
            versions = dict(self._versions)
        for name, version in versions.items():
            info = pb.ConfigInfo(name=name, version=version,
                                 status=pb.APPLIED)
            if name in feedback:
                status, msg = feedback.pop(name)
                info.status = _STATUS_MAP.get(status, pb.UNSET)
                info.message = msg
            req.continuous_pipeline_configs.append(info)
        for name, (status, msg) in feedback.items():
            # feedback for configs we no longer hold (e.g. just removed)
            req.continuous_pipeline_configs.append(pb.ConfigInfo(
                name=name, version=self._versions.get(name, 0),
                status=_STATUS_MAP.get(status, pb.UNSET), message=msg))
        return req

    def _run(self) -> None:
        while self._running:
            ok = False
            try:
                ok = self.heartbeat_once()
            except Exception:  # noqa: BLE001
                log.exception("heartbeat failed")
            # exponential backoff + jitter on failure (reference providers
            # never hammer a down server); reset on success
            self._fail_streak = 0 if ok else min(self._fail_streak + 1, 6)
            delay = self.interval_s * (2 ** self._fail_streak
                                       if self._fail_streak else 1)
            delay = min(delay, self.interval_s * 6)
            delay *= 0.8 + 0.4 * random.random()          # ±20 % jitter
            deadline = time.monotonic() + delay
            while self._running and time.monotonic() < deadline:
                time.sleep(0.1)

    def heartbeat_once(self) -> bool:
        body = self._post("/Agent/Heartbeat",
                          self._heartbeat_request().encode())
        if body is None:
            return False
        try:
            resp = pb.HeartbeatResponse.parse(body)
        except ValueError:
            log.warning("undecodable heartbeat response (%d bytes)",
                        len(body))
            return False
        updates = resp.continuous_pipeline_config_updates
        if resp.flags & pb.RESP_FETCH_CONTINUOUS_PIPELINE_CONFIG_DETAIL \
                and updates:
            updates = self._fetch_pipeline_details(updates)
        self._apply_updates(updates)
        return True

    def _fetch_pipeline_details(self, updates):
        """Server sent names/versions only — fetch details explicitly
        (reference FetchPipelineConfigFromServer)."""
        req = pb.FetchConfigRequest()
        req.request_id = uuid.uuid4().hex.encode()
        req.instance_id = self.instance_id.encode()
        for u in updates:
            req.continuous_pipeline_configs.append(
                pb.ConfigInfo(name=u.name, version=u.version))
        body = self._post("/Agent/FetchPipelineConfig", req.encode())
        if body is None:
            return []
        try:
            resp = pb.FetchConfigResponse.parse(body)
        except ValueError:
            return []
        return resp.continuous_pipeline_config_updates

    def _apply_updates(self, updates) -> None:
        for cfg in updates:
            name = cfg.name
            if not _safe_name(name):
                log.warning("rejecting unsafe remote config name %r", name)
                continue
            path = os.path.join(self.config_dir, f"{name}.json")
            if cfg.version == -1:                      # removal sentinel
                if os.path.exists(path):
                    os.remove(path)
                with self._lock:
                    self._versions.pop(name, None)
                log.info("removed remote config %s", name)
                continue
            if self._versions.get(name) == cfg.version:
                continue
            if not cfg.detail:
                # detail-less update (server expected us to fetch, or sent
                # a hollow entry): do NOT record the version — a recorded
                # version would suppress the refetch forever
                log.warning("config %s v%d arrived without detail; "
                            "will retry", name, cfg.version)
                continue
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(cfg.detail)
            os.replace(tmp, path)
            with self._lock:
                self._versions[name] = cfg.version
            log.info("materialized remote config %s v%d", name, cfg.version)

    def _post(self, path: str, payload: bytes) -> Optional[bytes]:
        conn = None
        try:
            u = urlparse(self.endpoint)
            conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                        else http.client.HTTPConnection)
            conn = conn_cls(u.netloc, timeout=10)
            conn.request("POST", path, body=payload,
                         headers={"Content-Type": "application/x-protobuf"})
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            return body
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            if conn is not None:
                conn.close()
