"""loongchaos — deterministic fault injection for every I/O and device
boundary (ISSUE 2 tentpole; docs/robustness.md has the operator guide).

Usage:

    from loongcollector_tpu import chaos

    with chaos.active(chaos.ChaosPlan(seed=7, rules={
            "http_sink.send": chaos.FaultSpec(prob=0.5, max_faults=20)})):
        ...drive the pipeline; faults land deterministically...

    # or env-driven: LOONG_CHAOS_SEED=7 activates ChaosPlan.default(7)
    # at application start.

Disabled (the default), every `faultpoint()` call is a no-op check.
"""

from .plan import (ACTION_CORRUPT, ACTION_DELAY, ACTION_ERROR,
                   ACTION_PARTIAL, ALL_ACTIONS, ChaosFault, ChaosPlan,
                   Decision, FaultSpec)
from .plane import (ENV_SEED, active, current_plan, fault_counts,
                    faultpoint, hit_counts, install, install_from_env,
                    is_active, register_point, registered_points, schedule,
                    reset, schedule_by_point, uninstall)

__all__ = [
    "ACTION_CORRUPT", "ACTION_DELAY", "ACTION_ERROR", "ACTION_PARTIAL",
    "ALL_ACTIONS", "ChaosFault", "ChaosPlan", "Decision", "FaultSpec",
    "ENV_SEED", "active", "current_plan", "fault_counts", "faultpoint",
    "hit_counts", "install", "install_from_env", "is_active",
    "register_point", "registered_points", "schedule",
    "reset", "schedule_by_point", "uninstall",
]
