"""loongchaos: the process-wide fault-injection plane.

Every I/O and device boundary registers a named fault point at import time
(`register_point`) and calls `faultpoint(name, ...)` on each hit.  With no
plan installed the hit is a single module-global read and an immediate
return — the send/dispatch hot paths pay one predictable branch, nothing
else.  With a plan installed (programmatic `install()` or the
``LOONG_CHAOS_SEED`` env var via `install_from_env()`), each hit draws a
deterministic per-point decision (chaos/plan.py) and either

  * raises the site's typed fault (``exc`` class, default ChaosFault),
  * sleeps in-line (injected latency), or
  * returns the Decision for site-specific interpretation — partial acks
    (Kafka window prefix) and corrupt-at-rest (disk buffer) cannot be
    expressed as a raise, the owning site applies them.

The plane keeps a bounded schedule log of every injected fault for
reproducibility assertions, and exports fault counters through
monitor/metrics.py (category "agent", component "chaos").
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from .plan import (ACTION_CORRUPT, ACTION_CRASH, ACTION_DELAY, ACTION_ERROR,
                   ACTION_PARTIAL, ChaosFault, ChaosPlan, Decision)

ENV_SEED = "LOONG_CHAOS_SEED"
ENV_CRASH = "LOONG_CHAOS_CRASH"   # "point:nth" — SIGKILL at hit nth of point

_SCHEDULE_CAP = 100_000   # injected-fault log bound (soaks stay well under)

_lock = threading.Lock()
_plan: Optional[ChaosPlan] = None
_hits: Dict[str, int] = {}
_schedule: List[tuple] = []
_registered: Set[str] = set()
_metrics = None           # lazy MetricsRecord; created on first install


def register_point(name: str) -> str:
    """Declare a fault point (module import time).  Returns the name so
    call sites can keep a module-level constant: the registry is the
    catalogue `registered_points()` exposes to docs/tests/default plans."""
    with _lock:
        _registered.add(name)
    return name


def registered_points() -> List[str]:
    with _lock:
        return sorted(_registered)


def is_active() -> bool:
    return _plan is not None


def current_plan() -> Optional[ChaosPlan]:
    return _plan


def install(plan: ChaosPlan) -> None:
    """Activate `plan` process-wide; resets hit counts and the schedule
    log so every install starts a fresh, comparable run."""
    global _plan, _metrics
    with _lock:
        if _metrics is None:
            from ..monitor.metrics import MetricsRecord
            _metrics = MetricsRecord(category="agent",
                                     labels={"component": "chaos"})
        _hits.clear()
        del _schedule[:]
        _plan = plan
        _metrics.gauge("chaos_active").set(1.0)
        _metrics.gauge("chaos_seed").set(float(plan.seed))


def uninstall() -> None:
    global _plan
    with _lock:
        _plan = None
        if _metrics is not None:
            _metrics.gauge("chaos_active").set(0.0)


def reset() -> None:
    """Uninstall AND forget all hit counts / the schedule log.  Plain
    `uninstall` keeps them so a finished storm stays inspectable; tests
    that must not see a previous test's storm call this instead."""
    with _lock:
        _hits.clear()
        del _schedule[:]
    uninstall()


@contextlib.contextmanager
def active(plan: ChaosPlan):
    """Scoped installation for tests: `with chaos.active(plan): ...`."""
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


def install_from_env(env=os.environ) -> bool:
    """Install ChaosPlan.default(seed) when LOONG_CHAOS_SEED is set, and
    arm the process.crash family when LOONG_CHAOS_CRASH="point:nth" is set
    (with or without a seed storm — the crash harness usually wants ONLY
    the kill, an exact-name rule riding an otherwise silent plan).
    Called once at application start; returns True when chaos went live."""
    raw = env.get(ENV_SEED)
    crash_raw = env.get(ENV_CRASH)
    plan: Optional[ChaosPlan] = None
    if raw:
        try:
            plan = ChaosPlan.default(int(raw))
        except ValueError:
            plan = None
    if crash_raw:
        point, sep, nth = crash_raw.rpartition(":")
        try:
            if not sep:
                raise ValueError(crash_raw)
            if plan is None:
                plan = ChaosPlan(0, {})
            plan.crash(point, int(nth))
        except ValueError:
            pass
    if plan is None:
        return False
    install(plan)
    return True


def schedule() -> List[tuple]:
    """Injected-fault log: [(point, hit, action, delay_s, magnitude)].
    Two runs with the same seed and per-point hit counts produce equal
    per-point subsequences (global order may differ across threads)."""
    with _lock:
        return list(_schedule)


def schedule_by_point() -> Dict[str, List[tuple]]:
    """The schedule grouped per point — the thread-order-independent form
    determinism assertions compare."""
    out: Dict[str, List[tuple]] = {}
    for entry in schedule():
        out.setdefault(entry[0], []).append(entry)
    return out


def fault_counts() -> Dict[str, int]:
    """point -> injected faults so far (all actions)."""
    counts: Dict[str, int] = {}
    for entry in schedule():
        counts[entry[0]] = counts.get(entry[0], 0) + 1
    return counts


def hit_counts() -> Dict[str, int]:
    with _lock:
        return dict(_hits)


def faultpoint(name: str, exc: Optional[type] = None,
               raise_: bool = True) -> Optional[Decision]:
    """One hit at fault point `name`.

    Disabled plane: returns None after a single global read — the no-op
    fast path every boundary rides in production.

    Active plane: ERROR raises ``(exc or ChaosFault)`` (unless
    ``raise_=False``, for sites where an exception cannot propagate —
    they receive the Decision and degrade in their own vocabulary, e.g.
    a queue rejecting the push).  DELAY sleeps here and returns None.
    PARTIAL/CORRUPT return the Decision for the site to apply; sites
    that cannot interpret them may ignore the return value.
    """
    plan = _plan
    if plan is None:
        return None
    with _lock:
        if _plan is not plan:       # racing uninstall/reinstall
            return None
        hit = _hits.get(name, 0)
        _hits[name] = hit + 1
        decision = plan.decide(name, hit)
        if decision is None:
            return None
        if len(_schedule) < _SCHEDULE_CAP:
            _schedule.append(decision.key())
        if _metrics is not None:
            _metrics.counter("faults_injected_total").add(1)
            _metrics.counter(f"faults_{decision.action}_total").add(1)
    # every injection lands on the trace timeline (and the current span,
    # if one is open on this thread): a storm is one causal story —
    # injection → breaker transition → spill — not three disjoint logs
    from .. import trace
    if trace.is_active():
        trace.event("chaos.inject", point=name, hit=decision.hit,
                    action=decision.action)
    # ... and in the flight ring (outside the plane lock): a crash mid-
    # storm dumps exactly which injections preceded it (docs/observability)
    from ..prof import flight
    flight.record("chaos.inject", point=name, hit=decision.hit,
                  action=decision.action)
    if decision.action == ACTION_CRASH:
        # process.crash: die the way a real crash dies — SIGKILL, no
        # drain, no flight dump, no atexit.  Anything recovery needs must
        # already be durable; flushing state here would make the harness
        # kinder than reality
        os.kill(os.getpid(), 9)
        time.sleep(60)    # SIGKILL is asynchronous; never fall through
    if decision.action == ACTION_DELAY:
        time.sleep(decision.delay_s)
        return None
    if decision.action == ACTION_ERROR and raise_:
        raise (exc or ChaosFault)(
            f"chaos[{name}#{decision.hit}]: injected fault "
            f"(seed {plan.seed})")
    return decision
