"""Seeded fault schedules: WHAT to inject, decided deterministically.

A `ChaosPlan` binds a seed to a set of per-fault-point rules.  Decisions
are drawn from a *per-point* RNG stream derived from ``(seed, point)`` and
cached by hit index, so the decision for hit N of point P depends only on
(seed, P, N) — never on thread interleaving across points.  Re-running a
workload with the same seed replays the identical fault schedule at every
point that receives the same number of hits, which is what makes a failing
soak seed reproducible (ISSUE 2 acceptance: "re-running any failing seed
reproduces the identical fault schedule").
"""

from __future__ import annotations

import fnmatch
import random
from typing import Dict, List, Optional, Sequence, Tuple


class ChaosFault(RuntimeError):
    """Default typed fault raised at a fault point with no site-specific
    exception class.  Sites that own a richer error taxonomy (KafkaError,
    PulsarError, OSError...) pass theirs so injected faults travel the
    exact recovery path a real failure would."""


#: actions a fault point can be told to take
ACTION_ERROR = "error"      # raise the site's typed fault
ACTION_DELAY = "delay"      # sleep in-line (slow network / device)
ACTION_PARTIAL = "partial"  # partial ack: the site delivers a prefix only
ACTION_CORRUPT = "corrupt"  # corrupt-at-rest: the site garbles its output
ACTION_CRASH = "crash"      # process.crash: SIGKILL the process, no drain

# crash is deliberately NOT in ALL_ACTIONS: specs built with
# kinds=ALL_ACTIONS storm recoverable faults, and a probabilistic draw
# must never SIGKILL the process — crash only fires via at_hits arming
ALL_ACTIONS = (ACTION_ERROR, ACTION_DELAY, ACTION_PARTIAL, ACTION_CORRUPT)

_VALID_ACTIONS = ALL_ACTIONS + (ACTION_CRASH,)


class FaultSpec:
    """Per-point rule: how often to fault, with which actions.

    prob         per-hit fault probability
    kinds        actions drawn (uniformly) when a hit faults
    delay_range  (lo, hi) seconds for ACTION_DELAY
    max_faults   stop faulting after this many injected faults (the storm
                 "clears", letting recovery invariants be asserted);
                 None = never clears
    after_hits   first hits never fault (lets a system warm up)
    at_hits      exact 0-based hit numbers that fault DETERMINISTICALLY
                 (prob plays no part) with the FIRST action in `kinds` —
                 the process.crash family: "SIGKILL at the 3rd spill"
    """

    __slots__ = ("prob", "kinds", "delay_range", "max_faults", "after_hits",
                 "at_hits")

    def __init__(self, prob: float = 0.25,
                 kinds: Sequence[str] = (ACTION_ERROR,),
                 delay_range: Tuple[float, float] = (0.001, 0.02),
                 max_faults: Optional[int] = None,
                 after_hits: int = 0,
                 at_hits: Sequence[int] = ()):
        for k in kinds:
            if k not in _VALID_ACTIONS:
                raise ValueError(f"unknown fault action {k!r}")
        self.prob = float(prob)
        self.kinds = tuple(kinds)
        self.delay_range = (float(delay_range[0]), float(delay_range[1]))
        self.max_faults = max_faults
        self.after_hits = int(after_hits)
        self.at_hits = frozenset(int(h) for h in at_hits)


class Decision:
    """One per-hit verdict.  ``magnitude`` is a stable uniform draw in
    [0, 1) that sites scale to their own units (partial-ack prefix
    fraction, corruption offset)."""

    __slots__ = ("point", "hit", "action", "delay_s", "magnitude")

    def __init__(self, point: str, hit: int, action: str,
                 delay_s: float, magnitude: float):
        self.point = point
        self.hit = hit
        self.action = action
        self.delay_s = delay_s
        self.magnitude = magnitude

    def key(self) -> tuple:
        """Comparable identity for schedule-equality assertions."""
        return (self.point, self.hit, self.action,
                round(self.delay_s, 9), round(self.magnitude, 9))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"<Decision {self.point}#{self.hit} {self.action}"
                f" delay={self.delay_s:.4f} mag={self.magnitude:.4f}>")


class ChaosPlan:
    """seed + {point pattern: FaultSpec} → deterministic decision streams.

    Rule lookup: exact point name first, then ``fnmatch`` patterns in
    sorted order (longest pattern wins ties), so ``"disk_buffer.*"`` covers
    both write and replay while ``"disk_buffer.write"`` can still override.

    NOT internally locked: the plane serializes decide() under its own hit
    lock (one lock, not two, on the fault path).
    """

    def __init__(self, seed: int,
                 rules: Optional[Dict[str, FaultSpec]] = None):
        self.seed = int(seed)
        self.rules = dict(rules or {})
        self._streams: Dict[str, random.Random] = {}
        self._decisions: Dict[str, List[Optional[Decision]]] = {}
        self._faults_injected: Dict[str, int] = {}

    @classmethod
    def default(cls, seed: int, prob: float = 0.2,
                max_faults: Optional[int] = 64) -> "ChaosPlan":
        """The LOONG_CHAOS_SEED schedule: error+delay storms everywhere,
        clearing after `max_faults` per point so long-running agents
        recover instead of flapping forever."""
        return cls(seed, {"*": FaultSpec(
            prob=prob, kinds=(ACTION_ERROR, ACTION_DELAY),
            max_faults=max_faults)})

    def crash(self, point: str, nth: int) -> "ChaosPlan":
        """Arm the process.crash family: SIGKILL this process at the
        `nth` (0-based) hit of `point`.  Exact-name rules override any
        pattern rule, so a crash can ride on top of a default() storm.
        Returns self for chaining."""
        self.rules[point] = FaultSpec(prob=0.0, kinds=(ACTION_CRASH,),
                                      at_hits=(nth,))
        return self

    def spec_for(self, point: str) -> Optional[FaultSpec]:
        spec = self.rules.get(point)
        if spec is not None:
            return spec
        best: Optional[Tuple[int, str]] = None
        for pattern in self.rules:
            if fnmatch.fnmatchcase(point, pattern):
                cand = (len(pattern), pattern)
                if best is None or cand > best:
                    best = cand
        return self.rules[best[1]] if best is not None else None

    def decide(self, point: str, hit: int) -> Optional[Decision]:
        """Decision for hit number `hit` (0-based) of `point`; None = no
        fault.  Cached: asking again for the same (point, hit) returns the
        identical decision."""
        cache = self._decisions.setdefault(point, [])
        while len(cache) <= hit:
            cache.append(self._draw(point, len(cache)))
        return cache[hit]

    def _draw(self, point: str, hit: int) -> Optional[Decision]:
        spec = self.spec_for(point)
        if spec is None:
            return None
        rng = self._streams.get(point)
        if rng is None:
            rng = self._streams[point] = random.Random(
                f"{self.seed}:{point}")
        # one fixed-size draw block per hit keeps the stream aligned no
        # matter which branch a given hit takes
        roll = rng.random()
        kind_roll = rng.random()
        delay_roll = rng.random()
        magnitude = rng.random()
        if hit in spec.at_hits:
            # deterministic scheduled fault (process.crash): probability
            # plays no part, the first kind is the armed action
            self._faults_injected[point] = \
                self._faults_injected.get(point, 0) + 1
            return Decision(point, hit, spec.kinds[0], 0.0, magnitude)
        if hit < spec.after_hits or roll >= spec.prob:
            return None
        if spec.max_faults is not None and \
                self._faults_injected.get(point, 0) >= spec.max_faults:
            return None
        # crash never rides the probability roll — at_hits only (above)
        kinds = tuple(k for k in spec.kinds if k != ACTION_CRASH)
        if not kinds:
            return None
        self._faults_injected[point] = \
            self._faults_injected.get(point, 0) + 1
        action = kinds[int(kind_roll * len(kinds)) % len(kinds)]
        lo, hi = spec.delay_range
        delay_s = lo + (hi - lo) * delay_roll
        return Decision(point, hit, action, delay_s, magnitude)
