"""Payload compression for the send path.

Reference: core/common/compression/ — Compressor interface + LZ4/ZSTD impls,
CompressorFactory per flusher config.  The image bakes zlib/lzma in the
stdlib; LZ4/ZSTD are used when the optional modules exist, with zlib as the
always-available fallback (sinks negotiate the algorithm via config).
"""

from __future__ import annotations

import zlib
from typing import Optional

try:
    import zstandard as _zstd  # pragma: no cover - optional
except ImportError:
    _zstd = None


class Compressor:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        # serializers may hand over a memoryview; uncompressed payloads are
        # long-lived (sender queue, spill) so materialize here
        return data if isinstance(data, bytes) else bytes(data)

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return data if isinstance(data, bytes) else bytes(data)


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return zlib.decompress(data)


class LZ4Compressor(Compressor):
    """LZ4 BLOCK format via the native lib — SLS's default wire codec
    sends raw lz4 blocks with x-log-bodyrawsize carrying the raw size
    (FlusherSLS.h:124-159), not the frame format."""

    name = "lz4"

    def compress(self, data: bytes) -> bytes:
        from .. import native
        out = native.lz4_compress(data)
        if out is None:
            raise RuntimeError("lz4 codec unavailable (native lib missing)")
        return out

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        from .. import native
        out = native.lz4_decompress(data, raw_size)
        if out is None:
            raise RuntimeError("lz4 decompress failed")
        return out


class SnappyCompressor(Compressor):
    """Snappy block format via the native lib (Prometheus remote-write)."""

    name = "snappy"

    def compress(self, data: bytes) -> bytes:
        from .. import native
        out = native.snappy_compress(data)
        if out is None:
            raise RuntimeError("snappy codec unavailable")
        return out

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        from .. import native
        out = native.snappy_decompress(data)
        if out is None:
            raise RuntimeError("snappy decompress failed")
        return out


class ZstdCompressor(Compressor):
    name = "zstd"

    def __init__(self, level: int = 1):
        self._c = _zstd.ZstdCompressor(level=level)
        self._d = _zstd.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return self._d.decompress(data)


def _native_codecs_available() -> bool:
    from .. import native
    lib = native.get_lib()
    return lib is not None and hasattr(lib, "lct_lz4_compress")


def create_compressor(kind: Optional[str]) -> Compressor:
    kind = (kind or "none").lower()
    if kind in ("none", ""):
        return Compressor()
    if kind == "lz4" and _native_codecs_available():
        return LZ4Compressor()
    if kind == "snappy" and _native_codecs_available():
        return SnappyCompressor()
    if kind == "zstd" and _zstd is not None:
        return ZstdCompressor()
    if kind in ("zlib", "lz4", "zstd", "snappy"):
        return ZlibCompressor()   # last-resort fallback
    return Compressor()
