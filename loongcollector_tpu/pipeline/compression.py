"""Payload compression for the send path.

Reference: core/common/compression/ — Compressor interface + LZ4/ZSTD impls,
CompressorFactory per flusher config.  The image bakes zlib/lzma in the
stdlib; LZ4/ZSTD are used when the optional modules exist, with zlib as the
always-available fallback (sinks negotiate the algorithm via config).
"""

from __future__ import annotations

import zlib
from typing import Optional

try:
    import lz4.frame as _lz4  # pragma: no cover - optional
except ImportError:
    _lz4 = None

try:
    import zstandard as _zstd  # pragma: no cover - optional
except ImportError:
    _zstd = None


class Compressor:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return data


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return zlib.decompress(data)


class LZ4Compressor(Compressor):
    name = "lz4"

    def compress(self, data: bytes) -> bytes:
        return _lz4.compress(data)

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return _lz4.decompress(data)


class ZstdCompressor(Compressor):
    name = "zstd"

    def __init__(self, level: int = 1):
        self._c = _zstd.ZstdCompressor(level=level)
        self._d = _zstd.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def decompress(self, data: bytes, raw_size: int = 0) -> bytes:
        return self._d.decompress(data)


def create_compressor(kind: Optional[str]) -> Compressor:
    kind = (kind or "none").lower()
    if kind in ("none", ""):
        return Compressor()
    if kind == "zlib" or (kind == "lz4" and _lz4 is None) or (kind == "zstd" and _zstd is None):
        return ZlibCompressor()
    if kind == "lz4":
        return LZ4Compressor()
    if kind == "zstd":
        return ZstdCompressor()
    return Compressor()
