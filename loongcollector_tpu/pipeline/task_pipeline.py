"""Task pipelines: non-collection background jobs.

Reference: core/task_pipeline/ — TaskPipelineManager + TaskRegistry own
config-driven tasks that are not data pipelines (cleanup jobs, exporters);
same watch/diff lifecycle, no queue wiring.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from ..utils.logger import get_logger

log = get_logger("task_pipeline")


class Task:
    name = "task_base"

    def init(self, config: Dict[str, Any]) -> bool:
        self.config = config
        return True

    def start(self) -> bool:
        return True

    def stop(self) -> bool:
        return True


class TaskRegistry:
    _instance: Optional["TaskRegistry"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._creators: Dict[str, Callable[[], Task]] = {}

    @classmethod
    def instance(cls) -> "TaskRegistry":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, name: str, creator: Callable[[], Task]) -> None:
        self._creators[name] = creator

    def create(self, name: str) -> Optional[Task]:
        c = self._creators.get(name)
        return c() if c else None

    def is_valid(self, name: str) -> bool:
        return name in self._creators


class TaskPipelineManager:
    def __init__(self) -> None:
        self._tasks: Dict[str, Task] = {}
        self._lock = threading.Lock()

    def update_tasks(self, diff) -> None:
        """Same ConfigDiff contract as collection pipelines."""
        for name in diff.removed:
            with self._lock:
                task = self._tasks.pop(name, None)
            if task:
                task.stop()
                log.info("task %s removed", name)
        for name, cfg in list(diff.modified.items()) + list(diff.added.items()):
            task_cfg = cfg.get("task", {})
            typ = task_cfg.get("Type", "")
            task = TaskRegistry.instance().create(typ)
            if task is None or not task.init(task_cfg):
                log.error("task %s (%s) failed to init", name, typ)
                continue
            with self._lock:
                old = self._tasks.get(name)
                self._tasks[name] = task
            if old:
                old.stop()
            task.start()
            log.info("task %s started", name)

    def find(self, name: str) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(name)

    def stop_all(self) -> None:
        with self._lock:
            tasks = list(self._tasks.values())
            self._tasks.clear()
        for t in tasks:
            t.stop()
