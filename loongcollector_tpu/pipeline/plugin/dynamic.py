"""Dynamic out-of-tree plugins.

Reference: the PluginRegistry loads dynamic C processors via dlopen with a
versioned `processor_interface_t` vtable (PluginRegistry.cpp:233-290,
plugin/creator/CProcessor.h) — the cheap generality mechanism replacing the
reference's Go plugin runtime for long-tail needs (SURVEY.md §7 step 7).

Two loaders:
  * Python module plugins: `{"Type": "dynamic", "Module": "my_pkg.my_mod",
    "Class": "MyProcessor"}` — the class implements the Processor interface.
  * C ABI plugins: a shared library exporting the versioned vtable
        int  lct_processor_interface_version(void);
        void* lct_processor_create(const char* json_config);
        int  lct_processor_process(void* inst, const uint8_t* in, int64_t len,
                                   uint8_t** out, int64_t* out_len);
        void lct_processor_free_result(uint8_t* out);
        void lct_processor_destroy(void* inst);
    Process I/O is the JSON event-group fixture format (the stable ABI the
    test hooks already use), loaded with ctypes.
"""

from __future__ import annotations

import ctypes
import importlib
import json
from typing import Any, Dict, Optional

from ...models import PipelineEventGroup
from ...utils.logger import get_logger
from .interface import PluginContext, Processor

log = get_logger("dynamic_plugin")

C_ABI_VERSION = 1


class DynamicPythonProcessor(Processor):
    """Wraps a user-provided Processor class from an importable module."""

    name = "processor_dynamic"

    def __init__(self) -> None:
        super().__init__()
        self._inner: Optional[Processor] = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        module_name = config.get("Module", "")
        class_name = config.get("Class", "")
        if not module_name or not class_name:
            return False
        try:
            module = importlib.import_module(module_name)
            cls = getattr(module, class_name)
            self._inner = cls()
        except (ImportError, AttributeError) as e:
            log.error("dynamic plugin %s.%s failed to load: %s",
                      module_name, class_name, e)
            return False
        return self._inner.init(config.get("PluginConfig", {}), context)

    def process(self, group: PipelineEventGroup) -> None:
        if self._inner is not None:
            self._inner.process(group)


class DynamicCProcessor(Processor):
    """dlopen'd C-ABI processor (reference DynamicCProcessorProxy)."""

    name = "processor_dynamic_c"

    def __init__(self) -> None:
        super().__init__()
        self._lib = None
        self._inst = None

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        lib_path = config.get("Library", "")
        if not lib_path:
            return False
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            log.error("failed to load %s: %s", lib_path, e)
            return False
        try:
            lib.lct_processor_interface_version.restype = ctypes.c_int
            version = lib.lct_processor_interface_version()
        except AttributeError:
            log.error("%s does not export the processor vtable", lib_path)
            return False
        if version != C_ABI_VERSION:
            log.error("%s ABI version %d != %d", lib_path, version,
                      C_ABI_VERSION)
            return False
        lib.lct_processor_create.restype = ctypes.c_void_p
        lib.lct_processor_create.argtypes = [ctypes.c_char_p]
        lib.lct_processor_process.restype = ctypes.c_int
        lib.lct_processor_process.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64)]
        lib.lct_processor_free_result.argtypes = [
            ctypes.POINTER(ctypes.c_uint8)]
        lib.lct_processor_destroy.argtypes = [ctypes.c_void_p]
        cfg_json = json.dumps(config.get("PluginConfig", {})).encode()
        inst = lib.lct_processor_create(cfg_json)
        if not inst:
            return False
        self._lib = lib
        self._inst = inst
        return True

    def process(self, group: PipelineEventGroup) -> None:
        if self._lib is None:
            return
        data = group.to_json().encode()
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        out_ptr = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_int64(0)
        rc = self._lib.lct_processor_process(
            self._inst, buf, len(data), ctypes.byref(out_ptr),
            ctypes.byref(out_len))
        if rc != 0 or not out_ptr:
            return
        try:
            out = bytes(bytearray(out_ptr[: out_len.value]))
            new_group = PipelineEventGroup.from_json(out.decode("utf-8"))
        except (ValueError, KeyError, UnicodeDecodeError):
            return
        finally:
            self._lib.lct_processor_free_result(out_ptr)
        # splice the full transformed group back in (events + tags +
        # metadata — the ABI contract is the whole fixture document)
        group._events = new_group.events
        group._columns = None
        group._source_buffer = new_group.source_buffer
        group._tags = new_group._tags
        group._metadata = new_group._metadata

    def __del__(self):
        if self._lib is not None and self._inst:
            try:
                self._lib.lct_processor_destroy(self._inst)
            except Exception:  # noqa: BLE001
                pass
