"""Extension plugin layer: named, shareable helper components.

Reference: plugins/extension/ + pkg/pipeline/extensions/ — extensions are
plugin instances declared in a pipeline's `extensions:` section and
referenced BY NAME from other plugins' configs (an HTTP flusher points at
an authenticator, a request breaker, an encoder; an HTTP-server input
points at a decoder).  Roles mirror the reference interfaces:

  ClientAuthenticator  mutates an outgoing HttpRequest   (ext_basicauth)
  RequestInterceptor   gates sends / records outcomes    (ext_request_breaker)
  Decoder              bytes -> event groups             (ext_default_decoder)
  Encoder              event groups -> bytes             (ext_default_encoder)
  FlushInterceptor     drops/filters groups before send  (ext_groupinfo_filter)

Lookup: PluginContext.get_extension("<type>" or "<type>/<alias>") resolves
instances created by CollectionPipeline.init from the `extensions:` config
list; plugins keep working without any extensions configured.
"""

from __future__ import annotations

import base64
import threading
import time
from typing import Any, Dict, List, Optional

from .interface import Plugin, PluginContext


class Extension(Plugin):
    """Base for all extensions; `stop()` mirrors the reference lifecycle."""

    name = "extension_base"

    def stop(self) -> None:  # pragma: no cover — default no-op
        pass


# --------------------------------------------------------------- basicauth


class ExtBasicAuth(Extension):
    """plugins/extension/basicauth — adds Authorization to each request."""

    name = "ext_basicauth"

    def __init__(self) -> None:
        super().__init__()
        self._header = ""

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        user = config.get("Username", "")
        pwd = config.get("Password", "")
        if not user:
            return False
        token = base64.b64encode(f"{user}:{pwd}".encode()).decode()
        self._header = f"Basic {token}"
        return True

    def apply(self, request) -> None:
        """ClientAuthenticator: mutate the outgoing HttpRequest."""
        request.headers["Authorization"] = self._header


# ---------------------------------------------------------- request breaker


class BreakerOpen(RuntimeError):
    pass


class ExtRequestBreaker(Extension):
    """plugins/extension/request_breaker — fail-fast circuit breaker.

    Sliding-window failure ratio: when the ratio of failed sends within
    WindowInSeconds exceeds FailureRatio, allow() returns False (callers
    fail fast without hitting the endpoint) until the window cools down.
    A half-open probe is let through once per cooldown interval."""

    name = "ext_request_breaker"

    def __init__(self) -> None:
        super().__init__()
        self.failure_ratio = 0.10
        self.window_s = 10.0
        self._events: List = []          # (ts, ok)
        self._lock = threading.Lock()
        self._open_until = 0.0

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.failure_ratio = float(config.get("FailureRatio", 0.10))
        self.window_s = float(config.get("WindowInSeconds", 10) or 10)
        return True

    def allow(self) -> bool:
        now = time.monotonic()
        with self._lock:
            if now < self._open_until:
                return False
            self._trim(now)
            total = len(self._events)
            if total < 4:                # not enough signal to trip
                return True
            fails = sum(1 for _, ok in self._events if not ok)
            if fails / total > self.failure_ratio:
                # trip: fail fast for one window, then allow a probe
                self._open_until = now + self.window_s
                self._events.clear()
                return False
            return True

    def on_result(self, ok: bool) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, ok))
            self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.pop(0)


# ---------------------------------------------------------- decoder/encoder


class ExtDefaultDecoder(Extension):
    """plugins/extension/default_decoder — bytes → event groups by Format
    (json lines, sls protobuf, raw)."""

    name = "ext_default_decoder"

    def __init__(self) -> None:
        super().__init__()
        self.fmt = "json"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.fmt = str(config.get("Format", "json")).lower()
        return self.fmt in ("json", "sls", "sls_pb", "raw",
                            "statsd", "influx", "influxdb")

    def decode(self, body: bytes, headers: Optional[dict] = None):
        from ...models import PipelineEventGroup
        if self.fmt in ("sls", "sls_pb"):
            from ..serializer.sls_serializer import parse_loggroup
            return [parse_loggroup(body)]
        group = PipelineEventGroup()
        sb = group.source_buffer
        if self.fmt == "statsd":
            from ...input.metric_protocols import parse_statsd_packet
            return [group] if parse_statsd_packet(body, group) else []
        if self.fmt in ("influx", "influxdb"):
            from ...input.metric_protocols import parse_influx_lines
            return [group] if parse_influx_lines(body, group) else []
        if self.fmt == "raw":
            ev = group.add_log_event(int(time.time()))
            ev.set_content(sb.copy_string(b"content"), sb.copy_string(body))
            return [group]
        import json as _json
        for line in body.splitlines():
            if not line.strip():
                continue
            ev = group.add_log_event(int(time.time()))
            try:
                doc = _json.loads(line)
            except ValueError:
                ev.set_content(sb.copy_string(b"content"),
                               sb.copy_string(line))
                continue
            if isinstance(doc, dict):
                for k, v in doc.items():
                    if not isinstance(v, (bytes, str)):
                        v = _json.dumps(v)
                    ev.set_content(sb.copy_string(str(k).encode()),
                                   sb.copy_string(v.encode()
                                                  if isinstance(v, str)
                                                  else v))
            else:
                ev.set_content(sb.copy_string(b"content"),
                               sb.copy_string(line))
        return [group]


class ExtDefaultEncoder(Extension):
    """plugins/extension/default_encoder — event groups → bytes by Format
    (json lines or sls protobuf)."""

    name = "ext_default_encoder"

    def __init__(self) -> None:
        super().__init__()
        self.fmt = "json"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.fmt = str(config.get("Format", "json")).lower()
        return self.fmt in ("json", "sls", "sls_pb")

    def encode(self, groups) -> bytes:
        if self.fmt in ("sls", "sls_pb"):
            from ..serializer.sls_serializer import SLSEventGroupSerializer
            return SLSEventGroupSerializer().serialize(groups)
        from ..serializer.json_serializer import JsonSerializer
        return JsonSerializer().serialize(groups)


# ------------------------------------------------------- group info filter


class ExtGroupInfoFilter(Extension):
    """plugins/extension/group_info_filter — FlushInterceptor that keeps
    only groups whose tags match the configured exact values."""

    name = "ext_groupinfo_filter"

    def __init__(self) -> None:
        super().__init__()
        self.tags: Dict[bytes, bytes] = {}

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        for k, v in (config.get("Tags") or {}).items():
            self.tags[str(k).encode()] = str(v).encode()
        return True

    def filter(self, groups):
        if not self.tags:
            return list(groups)
        kept = []
        for g in groups:
            tags = {k: v.to_bytes() for k, v in g.tags.items()}
            if all(tags.get(k) == v for k, v in self.tags.items()):
                kept.append(g)
        return kept


ALL_EXTENSIONS = [ExtBasicAuth, ExtRequestBreaker, ExtDefaultDecoder,
                  ExtDefaultEncoder, ExtGroupInfoFilter]


def register_all(registry) -> None:
    for cls in ALL_EXTENSIONS:
        registry.register_extension(cls.name, cls)
