from .interface import Flusher, Input, Processor, PluginContext
from .registry import PluginRegistry
