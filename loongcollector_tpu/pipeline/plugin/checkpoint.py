"""Plugin checkpoint store: small durable key→value state for plugins.

Reference: the Go plugin context's GetCheckPoint/SaveCheckPoint
(pkg/pipeline/context.go, backed by pluginmanager's leveldb checkpoint
dir) — rdb inputs persist their column checkpoint, kafka persists
offsets, etc.  Here: one JSON file, written atomically, keyed by
"<pipeline>/<key>" so pipeline reloads keep their state.

The store is process-global (set_default_store from Application);
without one (tests, ad-hoc runs) checkpoints are kept in memory only.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

from ...utils.logger import get_logger

log = get_logger("plugin_checkpoint")


class PluginCheckpointStore:
    def __init__(self, path: str = ""):
        self.path = path
        self._lock = threading.Lock()
        self._state: Dict[str, str] = {}
        self._dirty = False
        if path:
            self._load()

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict):
                self._state = {str(k): str(v) for k, v in data.items()}
        except (OSError, ValueError):
            pass

    def get(self, pipeline: str, key: str) -> Optional[str]:
        with self._lock:
            return self._state.get(f"{pipeline}/{key}")

    def save(self, pipeline: str, key: str, value: str) -> None:
        with self._lock:
            self._state[f"{pipeline}/{key}"] = value
            self._dirty = True

    def delete(self, pipeline: str, key: str) -> None:
        with self._lock:
            if self._state.pop(f"{pipeline}/{key}", None) is not None:
                self._dirty = True

    def flush(self) -> None:
        """Atomic write (tmp + rename); called on save-interval ticks and
        agent shutdown."""
        with self._lock:
            if not self._dirty or not self.path:
                return
            snapshot = dict(self._state)
            self._dirty = False
        tmp = self.path + ".tmp"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snapshot, f)
            os.replace(tmp, self.path)
        except OSError as e:
            log.warning("plugin checkpoint flush failed: %s", e)
            with self._lock:
                self._dirty = True


_default_store = PluginCheckpointStore()
_default_lock = threading.Lock()


def get_default_store() -> PluginCheckpointStore:
    return _default_store


def set_default_store(store: PluginCheckpointStore) -> None:
    global _default_store
    with _default_lock:
        _default_store = store
