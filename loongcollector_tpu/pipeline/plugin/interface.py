"""Plugin interfaces: Input / Processor / Flusher.

Reference: core/collection_pipeline/plugin/interface/{Input,Processor,
Flusher}.h — Init(config, context), Start/Stop for inputs, Process(group) for
processors, Send(group)/FlushAll for flushers.  Flusher::Send serializes into
its own sender queue (interface/Flusher.cpp:57).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ...models import PipelineEventGroup
from ...monitor import ledger, slo
from ...runner import ack_watermark


class PluginContext:
    """Per-pipeline context handed to every plugin instance (reference
    CollectionPipelineContext)."""

    def __init__(self, pipeline_name: str = "", config: Optional[dict] = None):
        self.pipeline_name = pipeline_name
        self.config = config or {}
        self.process_queue_key: int = 0
        self.process_queue_manager = None  # set by CollectionPipeline.init
        self.global_config: Dict[str, Any] = {}
        self.logger = None
        self.metrics = None
        self.pipeline = None  # set by CollectionPipeline.init
        # named extension instances from the pipeline's `extensions:`
        # section (reference pkg/pipeline/extensions); key = "<type>" or
        # "<type>/<alias>"
        self.extensions: Dict[str, Any] = {}

    def get_extension(self, ref: str):
        """Resolve an extension reference from another plugin's config."""
        return self.extensions.get(ref)

    # -- plugin checkpoints (reference pkg/pipeline/context.go
    #    GetCheckPoint/SaveCheckPoint) -------------------------------------

    def get_checkpoint(self, key: str):
        from .checkpoint import get_default_store
        return get_default_store().get(self.pipeline_name, key)

    def save_checkpoint(self, key: str, value: str) -> None:
        from .checkpoint import get_default_store
        get_default_store().save(self.pipeline_name, key, value)


class Plugin:
    name: str = "plugin_base"

    def __init__(self) -> None:
        self.context: Optional[PluginContext] = None
        self.metrics_record = None
        self.config: Dict[str, Any] = {}

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        self.context = context
        self.config = config
        return True


class Input(Plugin):
    """Inputs register with their singleton runner on start (reference
    Input::Start registers with e.g. FileServer / PrometheusInputRunner)."""

    name = "input_base"
    is_singleton = False   # singleton inputs: one instance across pipelines
    is_onetime = False     # onetime inputs: finite jobs with expiry

    def start(self) -> bool:  # pragma: no cover - interface
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        return True

    def supported_event_types(self) -> List[str]:
        return ["log"]


class Processor(Plugin):
    """Process mutates the group in place (reference Processor.h:28-37).

    Device-backed processors additionally implement the split dispatch /
    complete protocol (`supports_async_dispatch = True`): `process_dispatch`
    starts the device work and returns an opaque token; `process_complete`
    materialises it and applies the results.  The runner overlaps the device
    execution of group N with the host stages of its neighbours (SURVEY §7
    step 4 — the async device data plane)."""

    name = "processor_base"
    supports_async_dispatch = False

    #: loongcolumn capability flag: True ⇒ this plugin operates on
    #: ColumnarLogs span columns directly and never needs per-event dict
    #: access — columnar groups flow THROUGH it unmaterialized.  False ⇒
    #: the ProcessorInstance wrapper materializes per-event objects at
    #: this plugin's boundary (counted in models.churn_stats()) before
    #: calling it.  Declare it only when BOTH code paths are exercised by
    #: the columnar-vs-dict equivalence gate (docs/performance.md).
    supports_columnar = False

    #: True ⇒ this plugin ONLY understands span columns (no row path at
    #: all: the multiline split/merge family) — the instance wrapper must
    #: never materialize at its boundary, even in dict mode
    #: (``LOONG_COLUMNAR=0``), or the stage silently no-ops.  Implies
    #: supports_columnar.
    requires_columnar = False

    def process(self, group: PipelineEventGroup) -> None:  # pragma: no cover
        raise NotImplementedError

    def process_many(self, groups: List[PipelineEventGroup]) -> None:
        for g in groups:
            self.process(g)

    def process_dispatch(self, group: PipelineEventGroup):
        """Start work on `group`; device work may remain in flight.  The
        default (sync plugins) runs to completion and returns no token."""
        self.process(group)
        return None

    def fused_stage_spec(self, ctx):
        """loongresident: this plugin's device work in resident stage form
        (pipeline/fused_chain.FusedMemberStage), or None when it cannot
        join a fused pipeline program — not device-tier, inputs not
        statically bindable against ``ctx`` (FusionPlanContext), or the
        plugin simply has no device half.  Returning a member DOES NOT
        change the plugin's own process path: groups fusion cannot take
        still run it per-stage."""
        return None

    def process_complete(self, group: PipelineEventGroup, token) -> None:
        """Finish the work started by process_dispatch."""


class Flusher(Plugin):
    name = "flusher_base"

    #: loongledger: True for sinks whose ``send()`` terminates delivery
    #: inline (local file, stdout, blackhole, test checkers) — the
    #: FlusherInstance wrapper then ledgers ``send_ok`` centrally.  Sinks
    #: that queue/batch toward a network hop keep False and ledger at
    #: their real delivery boundary instead.
    ledger_terminal = False

    #: loongcolumn capability flag (the flusher-side mirror of
    #: Processor.supports_columnar): True ⇒ this sink's serialize path
    #: consumes span columns directly (the NDJSON-riding family, SLS wire,
    #: blackhole), so columnar groups reach the wire without ever minting
    #: per-event objects.  False ⇒ FlusherInstance materializes at send().
    supports_columnar = False

    def _ledger_pipeline(self) -> str:
        """Pipeline attribution for this sink's ledger records ("" when
        the flusher was never init()ed — tests driving bare plugins)."""
        return getattr(getattr(self, "context", None),
                       "pipeline_name", "") or ""

    def _ledger_drop(self, tag: str, n_events: int = 0, n_bytes: int = 0,
                     group: Optional[PipelineEventGroup] = None) -> None:
        """Reason-tagged terminal ``drop`` record for events this flusher
        discards — the shared shape of the B_DROP boilerplate.  Pass
        ``group`` to defer the O(events) count/size work until the ledger
        is confirmed on (the disabled-hook idiom)."""
        if group is not None:
            # a reasoned discard is terminal for the SOURCE span too: the
            # checkpoint watermark must advance past it (ledger on or off)
            ack_watermark.ack_groups([group], force=True)
            if slo.is_on():
                slo.observe_groups(self._ledger_pipeline(), [group],
                                   slo.OUTCOME_DROP)
        if not ledger.is_on():
            return
        if group is not None:
            n_events, n_bytes = len(group), group.data_size()
        ledger.record(self._ledger_pipeline(), ledger.B_DROP,
                      n_events, n_bytes, tag=tag)

    def _ledger_terminal_write(self, groups: List[PipelineEventGroup],
                               write_fn) -> bool:
        """Run ``write_fn()`` — the sink's actual write of ``groups`` —
        with the write-through terminal accounting around it: B_SEND_OK
        once the write lands, B_DROP tag=flush_write_failed when it
        raises.  The failure is terminal HERE (recorded + logged, not
        re-raised): the batch already left the batcher, nothing upstream
        can retry it, and an exception propagating into
        ProcessorRunner._send would record a second terminal
        (``send_error``) for the triggering group — a double count the
        auditor would report as a (negative) residual.  Returns False on
        a failed write."""
        led = ledger.is_on()
        if led:
            n_events = sum(len(g) for g in groups)
            n_bytes = sum(g.data_size() for g in groups)
        try:
            write_fn()
        except Exception:  # noqa: BLE001
            from ...utils.logger import get_logger
            get_logger("flusher").exception(
                "%s flush write failed; %d events dropped", self.name,
                sum(len(g) for g in groups))
            # terminal either way (nothing upstream retries a failed
            # write): the SOURCE spans are done — ack so the checkpoint
            # can advance instead of pinning on a dead batch
            ack_watermark.ack_groups(groups)
            if slo.is_on():
                slo.observe_groups(self._ledger_pipeline(), groups,
                                   slo.OUTCOME_DROP)
            if led:
                ledger.record(self._ledger_pipeline(), ledger.B_DROP,
                              n_events, n_bytes, tag="flush_write_failed")
            return False
        ack_watermark.ack_groups(groups)
        if slo.is_on():
            slo.observe_groups(self._ledger_pipeline(), groups,
                               slo.OUTCOME_SEND_OK)
        if led:
            ledger.record(self._ledger_pipeline(), ledger.B_SEND_OK,
                          n_events, n_bytes, tag=self.name)
        return True

    def __init__(self) -> None:
        super().__init__()
        self.queue_key: int = 0
        self.sender_queue = None
        self.plugin_id: str = ""  # set by the pipeline: "<type>/<index>"

    def spill_identity(self) -> Dict[str, str]:
        """Identity persisted with disk-buffered payloads; must uniquely
        address this flusher instance within its pipeline."""
        return {
            "pipeline": getattr(self.context, "pipeline_name", ""),
            "flusher_type": self.name,
            "plugin_id": self.plugin_id,
        }

    def send(self, group: PipelineEventGroup) -> bool:  # pragma: no cover
        raise NotImplementedError

    def flush(self, key: int = 0) -> bool:
        return True

    def flush_all(self) -> bool:
        return True

    def start(self) -> bool:
        return True

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        return True
