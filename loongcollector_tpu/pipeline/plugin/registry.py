"""Static plugin registry.

Reference: core/collection_pipeline/plugin/PluginRegistry.cpp —
LoadStaticPlugins (:162-231) registers creators; CreateInput/Processor/
Flusher (:112-133); unknown types raise (the reference classifies them as Go
plugins, :135-145 — this framework's extension mechanism is python entry
points registered at runtime instead).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Type

from .interface import Flusher, Input, Plugin, Processor


class PluginRegistry:
    _instance: Optional["PluginRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._inputs: Dict[str, Callable[[], Input]] = {}
        self._processors: Dict[str, Callable[[], Processor]] = {}
        self._flushers: Dict[str, Callable[[], Flusher]] = {}
        self._aggregators: Dict[str, Callable[[], Plugin]] = {}
        self._extensions: Dict[str, Callable[[], Plugin]] = {}
        self._loaded = False

    @classmethod
    def instance(cls) -> "PluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- registration -------------------------------------------------------

    def register_input(self, name: str, creator: Callable[[], Input]) -> None:
        self._inputs[name] = creator

    def register_processor(self, name: str, creator: Callable[[], Processor]) -> None:
        self._processors[name] = creator

    def register_flusher(self, name: str, creator: Callable[[], Flusher]) -> None:
        self._flushers[name] = creator

    def register_aggregator(self, name: str,
                            creator: Callable[[], Plugin]) -> None:
        self._aggregators[name] = creator

    def register_extension(self, name: str,
                           creator: Callable[[], Plugin]) -> None:
        self._extensions[name] = creator

    def load_static_plugins(self) -> None:
        """Registers all built-in plugins (idempotent)."""
        if self._loaded:
            return
        self._loaded = True
        from ... import aggregator as _aggregator_pkg
        from ... import flusher as _flusher_pkg
        from ... import input as _input_pkg
        from ... import processor as _processor_pkg
        _processor_pkg.register_all(self)
        _flusher_pkg.register_all(self)
        _input_pkg.register_all(self)
        _aggregator_pkg.register_all(self)
        from . import extension as _extension_pkg
        _extension_pkg.register_all(self)

    # -- creation -----------------------------------------------------------

    def create_input(self, name: str) -> Optional[Input]:
        c = self._inputs.get(name)
        return c() if c else None

    def create_processor(self, name: str) -> Optional[Processor]:
        c = self._processors.get(name)
        return c() if c else None

    def create_flusher(self, name: str) -> Optional[Flusher]:
        c = self._flushers.get(name)
        return c() if c else None

    def create_aggregator(self, name: str) -> Optional[Plugin]:
        c = self._aggregators.get(name)
        return c() if c else None

    def create_extension(self, name: str) -> Optional[Plugin]:
        c = self._extensions.get(name)
        return c() if c else None

    def is_valid_input(self, name: str) -> bool:
        return name in self._inputs

    def is_valid_processor(self, name: str) -> bool:
        return name in self._processors

    def is_valid_flusher(self, name: str) -> bool:
        return name in self._flushers
