"""Plugin instance wrappers: lifecycle + per-instance metrics.

Reference: core/collection_pipeline/plugin/instance/ — ProcessorInstance
times each Process call and counts in/out events; FlusherInstance and
InputInstance wrap lifecycle.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List

from ... import prof, trace
from ...models import PipelineEventGroup, columnar_enabled
from ...monitor import ledger, slo
from ...monitor.metrics import MetricsRecord
from ...runner import ack_watermark
from .interface import Flusher, Input, PluginContext, Processor


class ProcessorInstance:
    def __init__(self, plugin: Processor, plugin_id: str = ""):
        self.plugin = plugin
        self.plugin_id = plugin_id
        # loongcolumn: columnar groups pass through capable plugins
        # unmaterialized; everything else pays the (counted) expansion at
        # ITS boundary — never implicitly mid-plugin
        self.columnar_capable = bool(getattr(plugin, "supports_columnar",
                                             False))
        self._pipeline_name = ""
        self.metrics = MetricsRecord(
            category="plugin",
            labels={"plugin_type": plugin.name, "plugin_id": plugin_id})
        self.in_events = self.metrics.counter("in_events_total")
        self.out_events = self.metrics.counter("out_events_total")
        self.in_bytes = self.metrics.counter("in_size_bytes")
        self.cost_ms = self.metrics.counter("total_process_time_ms")
        # per-stage latency distribution (the ParPaRaw per-stage balance
        # view); the async device stage observes dispatch and complete
        # phases separately
        self.stage_hist = self.metrics.histogram("stage_seconds")

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        self.plugin.metrics_record = self.metrics
        self._pipeline_name = getattr(context, "pipeline_name", "") or ""
        return self.plugin.init(config, context)

    def _ledger_delta(self, n_in: int, groups: List[PipelineEventGroup]
                      ) -> None:
        """loongledger: a stage that changed the event population either
        minted events (split: process_expand) or retired/held them
        (filter, multiline carry: process_drop), attributed to this
        plugin.  Runs from the stage's finally so a raising stage still
        balances against whatever it left in the groups."""
        delta = sum(len(g) for g in groups) - n_in
        if delta > 0:
            ledger.record(self._pipeline_name, ledger.B_PROCESS_EXPAND,
                          delta, tag=self.plugin_id or self.plugin.name)
        elif delta < 0:
            ledger.record(self._pipeline_name, ledger.B_PROCESS_DROP,
                          -delta, tag=self.plugin_id or self.plugin.name)

    def _materialize_boundary(self, groups: List[PipelineEventGroup]) -> None:
        """The lazy materialization boundary (loongcolumn): a plugin that
        has not declared ``supports_columnar`` gets per-event objects,
        minted HERE — explicitly, attributed to this plugin id in
        models.churn_stats() — rather than implicitly wherever its body
        first touches ``group.events``.  With ``LOONG_COLUMNAR=0`` every
        boundary materializes: the dict path of the side-by-side bench."""
        if self.columnar_capable and columnar_enabled():
            return
        if getattr(self.plugin, "requires_columnar", False):
            # columnar-ONLY stage (multiline split/merge): materializing
            # here would no-op the stage — the dict path materializes at
            # the next row-capable boundary instead
            return
        where = self.plugin_id or self.plugin.name
        for g in groups:
            if g.is_columnar() and not g._events:
                g.materialize(where)

    def process(self, groups: List[PipelineEventGroup]) -> None:
        self._materialize_boundary(groups)
        n_in = sum(len(g) for g in groups)
        self.in_events.add(n_in)
        self.in_bytes.add(sum(g.data_size() for g in groups))
        tracer = trace.active_tracer()
        sp = (tracer.child_or_sampled("processor",
                                      "processor." + self.plugin.name)
              if tracer is not None else None)
        prof.push_marker("plugin", self.plugin_id or self.plugin.name)
        t0 = time.perf_counter()
        ok = False
        try:
            self.plugin.process_many(groups)
            ok = True
        finally:
            dt = time.perf_counter() - t0
            prof.pop_marker()
            self.stage_hist.observe(dt)
            self.cost_ms.add(int(dt * 1000))
            if sp is not None:
                sp.end(None if ok else "error")
            if ledger.is_on():
                self._ledger_delta(n_in, groups)
        self.out_events.add(sum(len(g) for g in groups))

    # -- async device plane (split dispatch/complete) -----------------------

    def process_dispatch(self, groups: List[PipelineEventGroup]):
        self._materialize_boundary(groups)
        n_in = sum(len(g) for g in groups)
        self.in_events.add(n_in)
        self.in_bytes.add(sum(g.data_size() for g in groups))
        tracer = trace.active_tracer()
        sp = (tracer.child_or_sampled("processor",
                                      "processor." + self.plugin.name
                                      + ".dispatch")
              if tracer is not None else None)
        prof.push_marker("plugin", self.plugin_id or self.plugin.name)
        t0 = time.perf_counter()
        ok = False
        try:
            tokens = [self.plugin.process_dispatch(g) for g in groups]
            ok = True
        finally:
            dt = time.perf_counter() - t0
            prof.pop_marker()
            self.stage_hist.observe(dt)
            self.cost_ms.add(int(dt * 1000))
            if sp is not None:
                sp.end(None if ok else "error")
            if ledger.is_on():
                self._ledger_delta(n_in, groups)
        return tokens

    def process_complete(self, groups: List[PipelineEventGroup],
                         tokens) -> None:
        n_in = sum(len(g) for g in groups)
        tracer = trace.active_tracer()
        sp = (tracer.child_or_sampled("processor",
                                      "processor." + self.plugin.name
                                      + ".complete")
              if tracer is not None else None)
        prof.push_marker("plugin", self.plugin_id or self.plugin.name)
        t0 = time.perf_counter()
        ok = False
        try:
            for g, tok in zip(groups, tokens):
                self.plugin.process_complete(g, tok)
            ok = True
        finally:
            dt = time.perf_counter() - t0
            prof.pop_marker()
            self.stage_hist.observe(dt)
            self.cost_ms.add(int(dt * 1000))
            if sp is not None:
                sp.end(None if ok else "error")
            if ledger.is_on():
                self._ledger_delta(n_in, groups)
        self.out_events.add(sum(len(g) for g in groups))


class InputInstance:
    def __init__(self, plugin: Input, plugin_id: str = ""):
        self.plugin = plugin
        self.plugin_id = plugin_id
        self.metrics = MetricsRecord(
            category="plugin",
            labels={"plugin_type": plugin.name, "plugin_id": plugin_id})

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        self.plugin.metrics_record = self.metrics
        return self.plugin.init(config, context)

    def start(self) -> bool:
        return self.plugin.start()

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        return self.plugin.stop(is_pipeline_removing)


class FlusherInstance:
    def __init__(self, plugin: Flusher, plugin_id: str = ""):
        self.plugin = plugin
        self.plugin_id = plugin_id
        self.metrics = MetricsRecord(
            category="plugin",
            labels={"plugin_type": plugin.name, "plugin_id": plugin_id})
        self.in_events = self.metrics.counter("in_events_total")
        self.in_groups = self.metrics.counter("in_event_groups_total")

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        self.plugin.metrics_record = self.metrics
        return self.plugin.init(config, context)

    def send(self, group: PipelineEventGroup) -> bool:
        # loongcolumn: the sink-side lazy materialization boundary — a
        # sink without columnar-capable serialization gets per-event
        # objects here (counted), the NDJSON/SLS-riding family never does
        if group.is_columnar() and not group._events \
                and not (columnar_enabled()
                         and getattr(self.plugin, "supports_columnar",
                                     False)):
            group.materialize(self.plugin_id or self.plugin.name)
        self.in_events.add(len(group))
        self.in_groups.add(1)
        # batch + serialize + sender-queue enqueue all live under the
        # flusher plugin's send — one span covers the serialize stage
        tracer = trace.active_tracer()
        sp = (tracer.child_or_sampled("flusher", "flusher.send",
                                      attrs={"flusher": self.plugin.name,
                                             "events": len(group)})
              if tracer is not None else None)
        ok = False
        try:
            result = self.plugin.send(group)
            ok = True
            if getattr(self.plugin, "ledger_terminal", False):
                # delivery (or refusal) completed inside send(): terminal
                # for the SOURCE span regardless of ledger state
                ack_watermark.ack_groups([group])
                if slo.is_on():
                    slo.observe_groups(
                        self.plugin._ledger_pipeline(), [group],
                        slo.OUTCOME_SEND_OK if result
                        else slo.OUTCOME_DROP)
            if ledger.is_on() and self.plugin.ledger_terminal:
                # inline-terminal sink: delivery completed (or was refused)
                # inside send() itself — ledger it here, once, centrally
                pname = self.plugin._ledger_pipeline()
                if result:
                    ledger.record(pname, ledger.B_SEND_OK, len(group),
                                  group.data_size(), tag=self.plugin.name)
                else:
                    ledger.record(pname, ledger.B_DROP, len(group),
                                  group.data_size(), tag="send_rejected")
            return result
        finally:
            if sp is not None:
                sp.end(None if ok else "error")

    def start(self) -> bool:
        return self.plugin.start()

    def stop(self, is_pipeline_removing: bool = False) -> bool:
        return self.plugin.stop(is_pipeline_removing)

    @property
    def queue_key(self) -> int:
        return self.plugin.queue_key
