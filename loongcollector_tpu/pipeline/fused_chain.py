"""loongresident pipeline glue: plan and execute fused stage runs.

`plan_fusion` walks a pipeline's processor chain at init time and asks
each plugin for its resident stage form (`Processor.fused_stage_spec`):
a maximal run of ≥ 2 consecutive fusable stages becomes a `FusedRun`
backed by ONE content-addressed `FusedProgramKernel`
(ops/fused_pipeline.py).  At process time the run packs the group's
source column once, dispatches the single fused program per chunk, and
applies each member stage's host-side epilogue in order over a row-index
map (a filter's compaction re-indexes every later member's outputs — the
fused program computed them for ALL packed rows, which is equivalent
because member stages are per-row independent).

Binding rules (`FusionPlanContext`): the run packs ONE source column;
members either consume those same rows or bind a PRIOR member's capture
column (device-resident span binding).  A stage whose inputs cannot be
proven statically — a field minted outside the run, a source key a prior
member consumed — refuses to fuse and ends the run; those stages keep
the per-stage dispatch path untouched.

Execution contract with CollectionPipeline.process_begin: a run behaves
like one async-dispatch-capable processor (dispatch → token →
complete), so the ProcessorRunner's overlap machinery, the stop/drain
barrier and the ledger's per-plugin delta accounting all keep working;
groups fusion cannot take (row-path groups, overlong rows, disabled
fusion) run the member instances per-stage inline — never dropped,
never reordered."""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..monitor import ledger
from ..ops.device_batch import LENGTH_BUCKETS
from ..ops.fused_pipeline import (FusedDispatch, fusion_enabled,
                                  get_fused_program)
from ..utils.logger import get_logger

log = get_logger("fused_chain")


class FusionPlanContext:
    """What the planner knows while growing one run: the packed source
    column, capture columns produced by prior members (name →
    (stage_idx, cap_idx)), and which keys a member consumed — the
    information that decides whether the NEXT stage's inputs are
    statically resident."""

    def __init__(self) -> None:
        self.source_key: Optional[bytes] = None
        self.consumed: set = set()
        self.fields: Dict[str, Tuple[int, int]] = {}
        self.n_stages = 0

    def bind_source(self, key: bytes) -> bool:
        """True when this stage may read the run's packed source rows."""
        skey = key.decode("latin-1") if isinstance(key, bytes) else key
        if skey in self.consumed:
            return False
        if self.source_key is None:
            self.source_key = key if isinstance(key, bytes) else key.encode()
            return True
        have = self.source_key.decode("latin-1")
        return skey == have

    def resolve(self, key) -> Optional[object]:
        """'source', ("capture", stage_idx, cap_idx), or None (not
        statically resident — the stage must not fuse)."""
        skey = key.decode("latin-1") if isinstance(key, bytes) else key
        got = self.fields.get(skey)
        if got is not None:
            return ("capture", got[0], got[1])
        if self.source_key is not None \
                and skey == self.source_key.decode("latin-1") \
                and skey not in self.consumed:
            return "source"
        if self.source_key is None:
            # a filter heading the run establishes the source column
            return "source"
        return None

    def note_fields(self, stage_idx: int, names: Sequence[str]) -> None:
        for cap, name in enumerate(names):
            if name:
                self.fields[name] = (stage_idx, cap)

    def note_consumed(self, key) -> None:
        skey = key.decode("latin-1") if isinstance(key, bytes) else key
        self.consumed.add(skey)


class FusedMemberStage:
    """One processor's contribution to a run: the resident StageSpec plus
    the host-side epilogue.  ``apply(group, src, stage_out, rowmap)``
    applies this stage's outputs (computed over the ORIGINAL packed rows;
    index via ``rowmap``) to the group and returns the new rowmap."""

    __slots__ = ("spec", "apply")

    def __init__(self, spec, apply):
        self.spec = spec
        self.apply = apply


class FusedRun:
    """A planned run of consecutive fusable stages [head, end) with its
    compiled program (built lazily via the content-addressed cache)."""

    def __init__(self, head: int, end: int, instances, members,
                 source_key: bytes):
        self.head = head
        self.end = end
        self.instances = list(instances)
        self.members: List[FusedMemberStage] = list(members)
        self.source_key = source_key
        self._program = None

    def enabled(self) -> bool:
        return fusion_enabled()

    def program(self):
        if self._program is None:
            self._program = get_fused_program(
                [m.spec for m in self.members])
        return self._program

    # -- execution ----------------------------------------------------------

    def dispatch(self, groups) -> List:
        """Per-group tokens; a group fusion cannot take runs the member
        instances per-stage INLINE here (synchronously — the fused plane's
        exception path, not its steady state) and gets a None token."""
        tokens: List = []
        for g in groups:
            tok = self._dispatch_group(g)
            if tok is None:
                for inst in self.instances:
                    inst.process([g])
            tokens.append(tok)
        return tokens

    def _dispatch_group(self, group):
        from ..processor.common import extract_source
        src = extract_source(group, self.source_key)
        if src is None or not src.columnar or len(src.offsets) == 0:
            return None
        if int(src.lengths.max()) > LENGTH_BUCKETS[-1]:
            # overlong rows keep the per-stage path (its CPU fallback
            # machinery owns them)
            return None
        try:
            d = FusedDispatch(self.program(), src.arena, src.offsets,
                              src.lengths).dispatch()
        except Exception:  # noqa: BLE001 — fusion must never lose a group
            log.exception("fused dispatch failed; group demoted to the "
                          "per-stage path")
            return None
        return (src, d)

    def complete(self, groups, tokens) -> None:
        for g, tok in zip(groups, tokens):
            if tok is None:
                continue
            src, d = tok
            res = d.result()
            rowmap = np.arange(res.n)
            for inst, member, out in zip(self.instances, self.members,
                                         res.stages):
                # in/out booked per member at ITS apply point, after the
                # previous members' compaction — the same funnel the
                # staged path reports (a fused filter's drop must show as
                # reduced input on the NEXT member, not phantom volume)
                n_before = len(g)
                inst.in_events.add(n_before)
                inst.in_bytes.add(g.data_size())
                t0 = time.perf_counter()
                ok = False
                try:
                    rowmap = member.apply(g, src, out, rowmap)
                    ok = True
                finally:
                    dt = time.perf_counter() - t0
                    inst.stage_hist.observe(dt)
                    inst.cost_ms.add(int(dt * 1000))
                    if ledger.is_on():
                        inst._ledger_delta(n_before, [g])
                    if ok:
                        inst.out_events.add(len(g))


def plan_fusion(chain) -> List[FusedRun]:
    """Walk the processor chain; every maximal run of ≥ 2 consecutive
    stages whose plugins produce a statically-bindable StageSpec becomes
    a FusedRun.  Planning is description — no jit, no device transfers
    (capture-bound filter conditions pay one host-side DFA determinize to
    prove fusability; their staged kernels build lazily on first
    demotion); the fused program compiles on first dispatch (or from the
    warm cache)."""
    runs: List[FusedRun] = []
    i = 0
    n = len(chain)
    while i < n:
        ctx = FusionPlanContext()
        members: List[FusedMemberStage] = []
        insts = []
        j = i
        while j < n:
            hook = getattr(chain[j].plugin, "fused_stage_spec", None)
            ms = None
            if hook is not None:
                try:
                    ms = hook(ctx)
                except Exception:  # noqa: BLE001 — a broken spec hook
                    # must degrade to the per-stage path, not kill init
                    log.exception("fused_stage_spec failed for %s",
                                  chain[j].plugin.name)
                    ms = None
            if ms is None:
                break
            ctx.n_stages += 1
            members.append(ms)
            insts.append(chain[j])
            j += 1
            if ms.spec.terminal:
                break
        if len(members) >= 2:
            runs.append(FusedRun(i, j, insts, members, ctx.source_key))
            i = j
        else:
            i += 1
    return runs
