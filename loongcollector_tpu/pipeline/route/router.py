"""Event routing to flushers.

Reference: core/collection_pipeline/route/Router.h:32-35 + Condition.h —
per-flusher match conditions (event-type / tag equality); Route(group)
returns the indices of flushers that should receive the group.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ...models import EventType, PipelineEventGroup

_EVENT_TYPES = {
    "log": EventType.LOG,
    "metric": EventType.METRIC,
    "trace": EventType.SPAN,
    "span": EventType.SPAN,
    "raw": EventType.RAW,
}


class Condition:
    """Match condition: {"Type": "event_type", "Value": "log"} or
    {"Type": "tag", "Key": ..., "Value": ...}."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self._config = config or {}
        self._kind = self._config.get("Type", "always")

    def check(self, group: PipelineEventGroup) -> bool:
        if self._kind == "always":
            return True
        if self._kind == "event_type":
            want = _EVENT_TYPES.get(str(self._config.get("Value", "")).lower())
            return want is not None and group.event_type() == want
        if self._kind == "tag":
            v = group.get_tag(self._config.get("Key", ""))
            return v is not None and v == str(self._config.get("Value", ""))
        return False


class Router:
    """Holds (flusher_idx, condition) pairs; unconditional flushers always
    receive the group."""

    def __init__(self) -> None:
        self._conditional: List[tuple] = []
        self._unconditional: List[int] = []

    def init(self, configs: List[tuple]) -> bool:
        """configs: list of (flusher_idx, match_config_or_None)."""
        for idx, cfg in configs:
            if cfg is None:
                self._unconditional.append(idx)
            else:
                self._conditional.append((idx, Condition(cfg)))
        return True

    def route(self, group: PipelineEventGroup) -> List[int]:
        out = list(self._unconditional)
        for idx, cond in self._conditional:
            if cond.check(group):
                out.append(idx)
        return out
