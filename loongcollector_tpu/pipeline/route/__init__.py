from .router import Condition, Router
