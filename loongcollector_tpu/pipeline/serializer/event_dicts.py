"""Shared event→dict projection for JSON-family sinks (ES bulk, ClickHouse
JSONEachRow, Loki push, OTLP/HTTP).

Mirrors JsonSerializer's field layout (one flat object per event, group tags
folded in) so every JSON sink ships the same shape the reference's Go
converter produces (pkg/protocol/converter). Columnar groups serialize
straight from span columns without materialising event objects.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

from ...models import (LogEvent, MetricEvent, PipelineEventGroup, RawEvent,
                       SpanEvent)
from ...models.events import metric_name_str as _name_str


def iter_event_dicts(group: PipelineEventGroup
                     ) -> Iterator[Tuple[int, Dict[str, object]]]:
    """Yields (timestamp_seconds, flat_dict) per event."""
    tags = {k.decode("utf-8", "replace"): str(v)
            for k, v in group.tags.items()}
    cols = group.columns
    if cols is not None and not group._events:
        raw = group.source_buffer.raw
        names = [n for n in (cols.fields or {}) if n != "_partial_"]
        spans = [cols.fields[n] for n in names]
        if not cols.content_consumed and "content" not in (cols.fields or {}):
            names.insert(0, "content")
            spans.insert(0, (cols.offsets, cols.lengths))
        tss = cols.timestamps
        for i in range(len(cols)):
            obj: Dict[str, object] = dict(tags)
            for name, (offs, lens) in zip(names, spans):
                ln = int(lens[i])
                if ln >= 0:
                    o = int(offs[i])
                    obj[name] = raw[o:o + ln].decode("utf-8", "replace")
            yield int(tss[i]), obj
        return
    # canonical dict fallback: event groups / already-materialized rows —
    # the one place the NDJSON family is ALLOWED to walk row objects
    for ev in group.events:  # loonglint: disable=hot-path-materialize
        obj = dict(tags)
        ts = 0
        if isinstance(ev, LogEvent):
            ts = ev.timestamp
            for k, v in ev.contents:
                obj[k.to_str()] = v.to_str()
        elif isinstance(ev, MetricEvent):
            ts = ev.timestamp
            obj["__name__"] = _name_str(ev.name)
            if ev.value.is_multi():
                obj["__values__"] = {k.decode(): v
                                     for k, v in ev.value.values.items()}
            else:
                obj["__value__"] = ev.value.value
            obj["__labels__"] = {k.decode(): str(v)
                                 for k, v in ev.tags.items()}
        elif isinstance(ev, SpanEvent):
            obj["traceId"] = ev.trace_id.decode("utf-8", "replace")
            obj["spanId"] = ev.span_id.decode("utf-8", "replace")
            obj["name"] = ev.name.decode("utf-8", "replace")
            obj["startTimeNs"] = ev.start_time_ns
            obj["endTimeNs"] = ev.end_time_ns
            ts = ev.start_time_ns // 1_000_000_000
        elif isinstance(ev, RawEvent):
            ts = ev.timestamp
            obj["content"] = str(ev.content) if ev.content else ""
        yield ts, obj


def collect_event_dicts(groups: List[PipelineEventGroup]
                        ) -> List[Tuple[int, Dict[str, object]]]:
    out: List[Tuple[int, Dict[str, object]]] = []
    for g in groups:
        out.extend(iter_event_dicts(g))
    return out
