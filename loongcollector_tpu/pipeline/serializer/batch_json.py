"""Batched NDJSON assembly shared by the JSON-family sinks (loongshard).

Before this module, JsonSerializer and four flushers (clickhouse / doris /
elasticsearch / loki) each ran the same loop: materialise a Python dict per
event, then ``json.dumps`` per row.  At pipeline rates that is the dominant
serialize cost — every field pays a bytes→str decode, a dict insert and a
re-encode, even though for columnar groups the values are untouched spans
of the SourceBuffer arena.

The fast path assembles output bytes once per group in native code
(``lct_ndjson_serialize``): cached group-tag prefix, cached per-column key
fragments, values escaped straight out of the arena.  Python only decides
eligibility — groups whose spans may hold non-ASCII bytes fall back to the
canonical dict path, because ``json.dumps`` + ``decode("utf-8", "replace")``
semantics for invalid UTF-8 belong to CPython, not to a C re-implementation.

Output is byte-identical to the dict path — ``json.dumps(obj,
ensure_ascii=False)`` with default separators — pinned by golden tests
(tests/test_batch_json.py).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ... import native
from ...models import PipelineEventGroup
from .event_dicts import iter_event_dicts

TS_NONE = native.NDJSON_TS_NONE
TS_EPOCH = native.NDJSON_TS_EPOCH
TS_ISO8601 = native.NDJSON_TS_ISO8601

# 1 where a byte is outside single-byte UTF-8 (>= 0x80): such spans must
# take the CPython path so invalid sequences get codec-identical treatment
_HIGH = np.zeros(256, dtype=np.uint8)
_HIGH[0x80:] = 1


def dumps_row(obj: Dict[str, object]) -> bytes:
    """The one canonical row encoder every JSON sink shares (identical to
    the four ``json.dumps(obj, ensure_ascii=False)`` copies it replaced)."""
    return json.dumps(obj, ensure_ascii=False).encode()


def decoded_tags(group: PipelineEventGroup) -> Dict[str, str]:
    """Group tags in the exact shape the dict path folds into every row."""
    return {k.decode("utf-8", "replace"): str(v)
            for k, v in group.tags.items()}


_frag_cache: Dict[str, bytes] = {}
_prefix_cache: Dict[Tuple[Tuple[str, str], ...], bytes] = {}


def _field_frag(name: str) -> bytes:
    """``"name": "`` — cached; schemas repeat for every group."""
    frag = _frag_cache.get(name)
    if frag is None:
        frag = (json.dumps(name, ensure_ascii=False) + ': "').encode()
        if len(_frag_cache) > 4096:      # unbounded schemas must not leak
            _frag_cache.clear()
        _frag_cache[name] = frag
    return frag


def tag_prefix(tags: Dict[str, str]) -> bytes:
    """``{"tag": "value"`` — the per-group constant head of every row
    (no trailing separator; the native writer adds ``, `` before the first
    member it appends).  Cached: steady-state pipelines re-emit identical
    tag sets for every group."""
    key = tuple(tags.items())
    pre = _prefix_cache.get(key)
    if pre is None:
        inner = ", ".join(
            f"{json.dumps(k, ensure_ascii=False)}: "
            f"{json.dumps(v, ensure_ascii=False)}" for k, v in tags.items())
        pre = ("{" + inner).encode()
        if len(_prefix_cache) > 1024:
            _prefix_cache.clear()
        _prefix_cache[key] = pre
    return pre


def _columnar_layout(group: PipelineEventGroup):
    """(names, offs [F,n] i32, lens [F,n] i32, tss) for the fast path, or
    None when the group is not columnar / the layout is not fast-safe.
    Field order matches iter_event_dicts exactly."""
    cols = group.columns
    if cols is None or group._events:
        return None
    fields = cols.fields or {}
    names = [n for n in fields if n != "_partial_"]
    spans = [fields[n] for n in names]
    if not cols.content_consumed and "content" not in fields:
        names.insert(0, "content")
        spans.insert(0, (cols.offsets, cols.lengths))
    if not names:
        return None
    if any(not isinstance(n, str) for n in names):
        return None
    try:
        offs = np.stack([np.asarray(s[0], dtype=np.int32) for s in spans])
        lens = np.stack([np.asarray(s[1], dtype=np.int32) for s in spans])
    except ValueError:
        return None
    return names, offs, lens, cols.timestamps


def _spans_are_ascii(group: PipelineEventGroup, offs: np.ndarray,
                     lens: np.ndarray) -> bool:
    """True when every present span is single-byte UTF-8 (no byte >=
    0x80).  Cheap max() over the arena answers the common machine-log case
    in one SIMD pass; only arenas that do contain high bytes pay the
    per-span cumulative-sum classification."""
    raw = group.source_buffer.raw
    if len(raw) == 0:
        return True
    arena = np.frombuffer(raw, dtype=np.uint8, count=len(raw))
    if int(arena.max()) < 0x80:
        return True
    csum = np.zeros(len(arena) + 1, dtype=np.int64)
    np.cumsum(_HIGH[arena], out=csum[1:])
    present = lens >= 0
    o = np.where(present, offs, 0).astype(np.int64)
    ln = np.where(present, lens, 0).astype(np.int64)
    e = np.minimum(o + ln, len(arena))
    o = np.minimum(o, len(arena))
    return not bool(((csum[e] - csum[o]) > 0).any())


def native_group_rows(group: PipelineEventGroup,
                      ts_key: Optional[str],
                      ts_mode: int = TS_EPOCH,
                      ts_first: bool = False,
                      suffix: bytes = b"\n",
                      head: bytes = b"",
                      ) -> Optional[memoryview]:
    """One group's NDJSON rows via the native assembler; None ⇒ the caller
    must run the canonical dict path for this group.  ``head`` is prepended
    to every row before the JSON object (ES bulk action lines)."""
    layout = _columnar_layout(group)
    if layout is None:
        return None
    names, offs, lens, tss = layout
    tags = decoded_tags(group)
    if ts_key is not None and (ts_key in names or ts_key in tags):
        # setdefault semantics: an existing field/tag wins — rare enough
        # that the dict path handles it wholesale
        return None
    if any(n in tags for n in names):
        # a field overwrites the same-named tag IN PLACE in the dict path;
        # the flat fast layout cannot reproduce that ordering
        return None
    if not _spans_are_ascii(group, offs, lens):
        return None
    prefix = head + tag_prefix(tags)
    ts_frag = b""
    if ts_key is not None and ts_mode != TS_NONE:
        ts_frag = (json.dumps(ts_key, ensure_ascii=False) + ": ").encode()
    else:
        ts_mode = TS_NONE
    return native.ndjson_serialize(
        np.frombuffer(group.source_buffer.raw, dtype=np.uint8,
                      count=len(group.source_buffer.raw)),
        np.asarray(tss, dtype=np.int64),
        tuple(_field_frag(n) for n in names),
        offs, lens, prefix, bool(tags), ts_frag, ts_mode, ts_first,
        suffix=suffix)


def ndjson_payload(groups: List[PipelineEventGroup],
                   ts_key: Optional[str] = None,
                   ts_mode: int = TS_EPOCH,
                   ) -> Optional[bytes]:
    """The shared NDJSON payload builder (clickhouse / doris): one JSON
    object per line, ``obj.setdefault(ts_key, ts)`` semantics, trailing
    newline after every row.  Columnar groups take the native zero-copy
    assembly; everything else rides the canonical dict path."""
    parts: List = []
    empty = True
    for g in groups:
        fast = native_group_rows(g, ts_key, ts_mode=ts_mode, ts_first=False)
        if fast is not None:
            if len(fast):
                empty = False
                parts.append(fast)
            continue
        for ts, obj in iter_event_dicts(g):
            if ts_key is not None:
                obj.setdefault(ts_key, ts)
            parts.append(dumps_row(obj))
            parts.append(b"\n")
            empty = False
    if empty:
        return None
    return b"".join(parts)
