"""Hand-rolled SLS LogGroup protobuf wire serializer.

Reference: core/collection_pipeline/serializer/SLSSerializer.cpp:162,221-245
and core/protobuf/sls/LogGroupSerializer.cpp — the reference writes protobuf
wire bytes directly (no intermediate PB objects) for speed; we do the same.

Wire schema (public sls_logs.proto):
  Log      { uint32 Time = 1; repeated Content Contents = 2;
             fixed32 Time_ns = 4; }
  Content  { string Key = 1; string Value = 2; }
  LogTag   { string Key = 1; string Value = 2; }
  LogGroup { repeated Log Logs = 1; string Category = 2; string Topic = 3;
             string Source = 4; string MachineUUID = 5;
             repeated LogTag LogTags = 6; }

Columnar fast path serializes straight from field span columns.
"""

from __future__ import annotations

from typing import List, Optional

from ...models import LogEvent, PipelineEventGroup


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _len_delim(field_no: int, payload: bytes) -> bytes:
    return _varint((field_no << 3) | 2) + _varint(len(payload)) + payload


def _kv(key: bytes, value: bytes) -> bytes:
    # Content/LogTag share the {Key=1, Value=2} shape
    return (b"\x0a" + _varint(len(key)) + key
            + b"\x12" + _varint(len(value)) + value)


class SLSEventGroupSerializer:
    name = "sls"

    def __init__(self, topic: bytes = b"", source: bytes = b"",
                 machine_uuid: bytes = b""):
        self.topic = topic
        self.source = source
        self.machine_uuid = machine_uuid

    def serialize(self, groups: List[PipelineEventGroup]) -> bytes:
        return b"".join(self._parts(groups))

    def _parts(self, groups: List[PipelineEventGroup]) -> List:
        # parts are joined exactly once by the caller; the native payload
        # part is a memoryview over the native output buffer (zero interim
        # copies)
        parts: List = []
        for group in groups:
            cols = group.columns
            # columnar fast path also covers the raw-tail case (no parsed
            # fields, just content spans) — falling through there would
            # materialize every line into a Python event (the reference's
            # 546 MB/s simple-line scenario lives on this path)
            if cols is not None and not group._events \
                    and (cols.fields or not cols.content_consumed):
                data = self._native_logs(group, cols)
                if data is not None:
                    parts.append(data)
                else:
                    buf = bytearray()
                    self._python_logs_from_columns(group, buf)
                    parts.append(buf)
            else:
                # canonical row fallback: groups that arrived materialized
                for ev in group.events:  # loonglint: disable=hot-path-materialize
                    if isinstance(ev, LogEvent):
                        parts.append(_len_delim(1, self._log(ev)))
            for k, v in group.tags.items():
                parts.append(_len_delim(6, _kv(k, v.to_bytes())))
        if self.topic:
            parts.append(_len_delim(3, self.topic))
        if self.source:
            parts.append(_len_delim(4, self.source))
        if self.machine_uuid:
            parts.append(_len_delim(5, self.machine_uuid))
        return parts

    def serialize_view(self, groups: List[PipelineEventGroup]):
        """Like serialize(), but may return a memoryview over the native
        output buffer when the payload is a single part (no tags/topic) —
        hot sinks (blackhole, SLS→LZ4) avoid one full-payload copy.  The
        result supports len()/buffer protocol but NOT bytes concatenation."""
        parts = self._parts(groups)
        if len(parts) == 1:
            return parts[0]
        return b"".join(parts)

    def _log(self, ev: LogEvent) -> bytes:
        body = bytearray(b"\x08" + _varint(ev.timestamp & 0xFFFFFFFF))
        for k, v in ev.contents:
            body += _len_delim(2, _kv(k.to_bytes(), v.to_bytes()))
        return bytes(body)

    @staticmethod
    def _columnar_spans(cols):
        names = [(n.encode() if isinstance(n, str) else n)
                 for n in cols.fields if n != "_partial_"]
        spans = [cols.fields[n] for n in cols.fields if n != "_partial_"]
        if not cols.content_consumed and b"content" not in names:
            names.insert(0, b"content")
            spans.insert(0, (cols.offsets, cols.lengths))
        return names, spans

    def _python_logs_from_columns(self, group: PipelineEventGroup,
                                  out: bytearray) -> None:
        cols = group.columns
        raw = group.source_buffer.raw
        names, spans = self._columnar_spans(cols)
        key_prefix = [b"\x0a" + _varint(len(n)) + n for n in names]
        tss = cols.timestamps
        for i in range(len(cols)):
            body = bytearray(b"\x08" + _varint(int(tss[i]) & 0xFFFFFFFF))
            for kp, (offs, lens) in zip(key_prefix, spans):
                ln = int(lens[i])
                if ln >= 0:
                    o = int(offs[i])
                    val = bytes(raw[o : o + ln])
                    content = kp + b"\x12" + _varint(ln) + val
                    body += b"\x12" + _varint(len(content)) + content
            out += b"\x0a" + _varint(len(body)) + body

    @staticmethod
    def _matrix_is_current(cols, m) -> bool:
        """The span_matrix fast path is valid only while cols.fields still
        IS the matrix: same names, same column-view tuples (by identity).
        Processors that mutate cols.fields directly (rename / drop /
        replace) bypass set_field's invalidation — detect that here instead
        of trusting the handle."""
        names, _off_mat, _len_mat, views = m
        if len(cols.fields) != len(names):
            return False
        for name, view in zip(names, views):
            if cols.fields.get(name) is not view:
                return False
        return True

    @classmethod
    def _native_logs(cls, group: PipelineEventGroup, cols):
        import numpy as _np

        from ... import native as _native
        if _native.get_lib() is None:
            return None
        m = cols.span_matrix
        if m is not None and cols.content_consumed \
                and cls._matrix_is_current(cols, m):
            # parse-kernel matrices cover the fields exactly: serialize the
            # [N, F] layout in place, no transpose/stack
            names, off_mat, len_mat, _views = m
            names = [(n.encode() if isinstance(n, str) else n)
                     for n in names]
            return _native.sls_serialize(group.source_buffer.as_array(),
                                         cols.timestamps, names,
                                         off_mat, len_mat, event_major=True)
        names, spans = cls._columnar_spans(cols)
        if not names:
            return None
        field_offs = _np.stack([s[0] for s in spans])
        field_lens = _np.stack([s[1] for s in spans])
        return _native.sls_serialize(group.source_buffer.as_array(),
                                     cols.timestamps, names,
                                     field_offs, field_lens)


def parse_loggroup(data: bytes, group: Optional[PipelineEventGroup] = None
                   ) -> PipelineEventGroup:
    """Decode LogGroup wire bytes back into an event group (the ingest-side
    mirror of the serializer; reference ProcessorParseFromPBNative decodes
    PB-transferred groups on the forward path).  Passing `group` decodes
    straight into its SourceBuffer — the forward path copies each string
    exactly once."""

    def read_varint(buf: bytes, i: int):
        shift = v = 0
        while True:
            b = buf[i]
            i += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v, i
            shift += 7

    def read_delim(buf: bytes, i: int):
        ln, i = read_varint(buf, i)
        if i + ln > len(buf):
            raise ValueError("truncated length-delimited field")
        return buf[i : i + ln], i + ln

    def parse_kv(buf: bytes):
        """{Key=1, Value=2} message (Content / LogTag share the shape)."""
        k = v = b""
        c = 0
        while c < len(buf):
            t3, c = read_varint(buf, c)
            payload, c = read_delim(buf, c)
            if t3 >> 3 == 1:
                k = payload
            elif t3 >> 3 == 2:
                v = payload
        return k, v

    if group is None:
        group = PipelineEventGroup()
    sb = group.source_buffer
    i = 0
    n = len(data)
    while i < n:
        tag, i = read_varint(data, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 2:
            payload, i = read_delim(data, i)
            if fno == 1:        # Log — ingest-side DECODE, not the wire
                # hot path: PB-transferred rows become events by design
                ev = group.add_log_event(0)  # loonglint: disable=hot-path-materialize
                j = 0
                while j < len(payload):
                    t2, j = read_varint(payload, j)
                    f2, w2 = t2 >> 3, t2 & 7
                    if f2 == 1 and w2 == 0:       # Time
                        ts, j = read_varint(payload, j)
                        ev.timestamp = ts
                    elif f2 == 2 and w2 == 2:     # Content
                        content, j = read_delim(payload, j)
                        k, v = parse_kv(content)
                        ev.set_content(sb.copy_string(k), sb.copy_string(v))
                    elif w2 == 2:
                        _, j = read_delim(payload, j)
                    elif w2 == 0:
                        _, j = read_varint(payload, j)
                    elif w2 == 5:
                        j += 4
                    else:
                        j += 8
            elif fno == 3:      # Topic
                group.set_tag(b"__topic__", payload)
            elif fno == 4:      # Source
                group.set_tag(b"__source__", payload)
            elif fno == 6:      # LogTag
                k, v = parse_kv(payload)
                group.set_tag(k, v)
        elif wt == 0:
            _, i = read_varint(data, i)
        elif wt == 5:
            i += 4
        else:
            i += 8
    return group
