"""JSON line serializer for JSON sinks (reference
core/collection_pipeline/serializer/JsonSerializer.cpp — one JSON object per
event with group tags folded in).

Columnar fast path (loongshard): rows are assembled in native code straight
from the SourceBuffer arena spans — cached group-tag prefix, cached key
fragments, no per-event dict, no per-event ``json.dumps`` (batch_json).
Event groups and non-ASCII payloads keep the original dict path; output is
byte-identical either way.
"""

from __future__ import annotations

import json
from typing import List

from ...models import (EventType, LogEvent, MetricEvent, PipelineEventGroup,
                       RawEvent, SpanEvent)


from ...models.events import metric_name_str as _name_str

from .batch_json import TS_EPOCH, native_group_rows

class JsonSerializer:
    name = "json"

    def serialize_view(self, groups: List[PipelineEventGroup]):
        """Serializer-interface hook: may return a memoryview when a
        zero-copy path exists (see SLSEventGroupSerializer); here it is
        just serialize()."""
        return self.serialize(groups)

    def serialize(self, groups: List[PipelineEventGroup]) -> bytes:
        parts: List = []
        for group in groups:
            cols = group.columns
            # the raw-tail case (no parsed fields, just content spans) is
            # columnar too — falling through would materialize every line
            # into a Python event (loonglint hot-path-materialize)
            columnar = (cols is not None and not group._events
                        and (cols.fields or not cols.content_consumed))
            if columnar:
                # native zero-copy assembly; None ⇒ dict fallback (event
                # groups, non-ASCII spans, key collisions)
                fast = native_group_rows(group, "__time__",
                                         ts_mode=TS_EPOCH, ts_first=True)
                if fast is not None:
                    if len(fast):
                        parts.append(fast)
                    continue
            out: List[str] = []
            tags = {k.decode("utf-8", "replace"): str(v)
                    for k, v in group.tags.items()}
            if columnar:
                self._serialize_columnar(group, tags, out)
            else:
                self._serialize_events(group, tags, out)
            if out:
                parts.append(("\n".join(out) + "\n").encode("utf-8"))
        return b"".join(parts) if parts else b""

    def _serialize_events(self, group: PipelineEventGroup, tags: dict,
                          out: List[str]) -> None:
        # canonical dict fallback (non-LOG events, materialized groups)
        for ev in group.events:  # loonglint: disable=hot-path-materialize
            obj = dict(tags)
            if isinstance(ev, LogEvent):
                obj["__time__"] = ev.timestamp
                for k, v in ev.contents:
                    obj[k.to_str()] = v.to_str()
            elif isinstance(ev, MetricEvent):
                obj["__time__"] = ev.timestamp
                obj["__name__"] = _name_str(ev.name)
                if ev.value.is_multi():
                    obj["__values__"] = {k.decode(): v for k, v in ev.value.values.items()}
                else:
                    obj["__value__"] = ev.value.value
                obj["__labels__"] = {k.decode(): str(v) for k, v in ev.tags.items()}
            elif isinstance(ev, SpanEvent):
                obj["traceId"] = ev.trace_id.decode("utf-8", "replace")
                obj["spanId"] = ev.span_id.decode("utf-8", "replace")
                obj["name"] = ev.name.decode("utf-8", "replace")
                obj["startTimeNs"] = ev.start_time_ns
                obj["endTimeNs"] = ev.end_time_ns
            elif isinstance(ev, RawEvent):
                obj["__time__"] = ev.timestamp
                obj["content"] = str(ev.content) if ev.content else ""
            out.append(json.dumps(obj, ensure_ascii=False))

    def _serialize_columnar(self, group: PipelineEventGroup, tags: dict,
                            out: List[str]) -> None:
        cols = group.columns
        raw = group.source_buffer.raw
        names = [n for n in cols.fields if n != "_partial_"]
        spans = [cols.fields[n] for n in names]
        if not cols.content_consumed and "content" not in cols.fields:
            names.insert(0, "content")
            spans.insert(0, (cols.offsets, cols.lengths))
        tss = cols.timestamps
        for i in range(len(cols)):
            obj = dict(tags)
            obj["__time__"] = int(tss[i])
            for name, (offs, lens) in zip(names, spans):
                ln = int(lens[i])
                if ln >= 0:
                    o = int(offs[i])
                    obj[name] = raw[o : o + ln].decode("utf-8", "replace")
            out.append(json.dumps(obj, ensure_ascii=False))
