from .batch_json import dumps_row, native_group_rows, ndjson_payload
from .json_serializer import JsonSerializer
from .sls_serializer import SLSEventGroupSerializer
