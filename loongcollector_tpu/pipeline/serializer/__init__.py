from .json_serializer import JsonSerializer
from .sls_serializer import SLSEventGroupSerializer
