"""Pipeline manager: named pipelines + generation-stamped hot swap.

Reference: core/collection_pipeline/CollectionPipelineManager.cpp
UpdatePipelines(diff) — the reference agent's defining production feature:
configs swap on a RUNNING agent without dropping events.

loongtenant rebuilds the swap as a **generation-stamped drain-and-handoff**
(docs/robustness.md#hot-reload--tenant-isolation):

  * each applied config creates generation N+1, which inits, brings its
    sink side up and REGISTERS under the name BEFORE generation N stops —
    the shared process queue key resolves to the new chain the moment it
    flips, so admission never pauses;
  * generation N then drains source-to-sink through the existing
    watermark queues (inputs stop, in-process groups finish, held
    processor state + batchers flush through N's own chain); serialized
    payloads a WEDGED sink cannot drain within ``reload_drain_timeout``
    spill to the disk buffer under ``enable_full_drain_mode`` (ledger
    B_SPILL — replay re-delivers when the sink recovers);
  * a failed N+1 init **rolls back**: generation N is never touched and
    keeps serving traffic; the failure is alarmed
    (``CONFIG_UPDATE_FAILED``), counted and flight-recorded.  This
    replaces the pre-loongtenant behaviour that dropped the OLD pipeline
    too ("keeping none") — the failure mode a fleet rollout of one bad
    YAML turns into a total collection outage;
  * every apply/remove passes the chaos point ``pipeline_manager.update``
    — an injected ERROR is a failed apply (rollback) or a deferred
    removal (the pipeline keeps running; retried on the next update);
  * per-tenant device-budget shares register with the DevicePlane
    (ops/device_plane.register_tenant) so hundreds of concurrent tenant
    pipelines split the in-flight byte budget instead of starving each
    other.

Removed pipelines stop with is_removing=True and their queues are GC'd.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .. import chaos
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..prof import flight
from ..utils import flags
from ..utils.logger import get_logger
from .pipeline import CollectionPipeline

log = get_logger("pipeline_manager")

#: control-plane chaos point: one hit per pipeline apply/remove inside
#: update_pipelines — an injected ERROR exercises the rollback / deferred-
#: removal paths, DELAY models a slow control plane (docs/robustness.md)
FP_UPDATE = chaos.register_point("pipeline_manager.update")

# how long a hot reload waits for the OLD generation's sender queues to
# drain before spilling the remainder to disk (enable_full_drain_mode)
flags.DEFINE_FLAG_DOUBLE(
    "reload_drain_timeout",
    "seconds a reload waits for the old generation's sender queues "
    "before spilling to the disk buffer", 2.0)

# observe-only handle for /debug/status (monitor/exposition.py): the most
# recently constructed manager — never constructed, never mutated through
# this; stop_all() clears it (runner/processor_runner.py idiom)
_active_manager = None

# -- reload telemetry (module-shared: managers come and go in tests, the
#    counters are process-lifetime) ------------------------------------------

_reload_metrics = None
_reload_metrics_lock = threading.Lock()


def reload_metrics():
    """``pipeline_reloads_total`` / ``config_update_failed_total`` /
    ``pipeline_removals_total`` counters (component=pipeline_manager).
    Double-checked lock: concurrent first reloads must not
    double-register the record (the aggregator-base race shape)."""
    global _reload_metrics
    if _reload_metrics is None:
        with _reload_metrics_lock:
            if _reload_metrics is None:
                from ..monitor.metrics import MetricsRecord
                _reload_metrics = MetricsRecord(
                    category="component",
                    labels={"component": "pipeline_manager"})
    return _reload_metrics


_reload_hist = None
_drain_hist = None


def reload_histogram():
    """``pipeline_reload_seconds``: wall time of one successful config
    apply (init → handoff → old-generation drain → inputs started)."""
    global _reload_hist
    if _reload_hist is None:
        from ..monitor.metrics import shared_histogram
        _reload_hist = shared_histogram(
            "pipeline_reload_seconds",
            labels={"component": "pipeline_manager"})
    return _reload_hist


def drain_histogram():
    """``pipeline_reload_drain_seconds``: the old-generation drain slice
    of a reload — the number that grows when a sink wedges."""
    global _drain_hist
    if _drain_hist is None:
        from ..monitor.metrics import shared_histogram
        _drain_hist = shared_histogram(
            "pipeline_reload_drain_seconds",
            labels={"component": "pipeline_manager"})
    return _drain_hist


class ConfigDiff:
    def __init__(self) -> None:
        self.added: Dict[str, dict] = {}
        self.modified: Dict[str, dict] = {}
        self.removed: List[str] = []

    def empty(self) -> bool:
        return not (self.added or self.modified or self.removed)


class CollectionPipelineManager:
    def __init__(self, process_queue_manager=None, sender_queue_manager=None):
        self._pipelines: Dict[str, CollectionPipeline] = {}
        self._lock = threading.Lock()
        self.process_queue_manager = process_queue_manager
        self.sender_queue_manager = sender_queue_manager
        self.onetime_manager = None  # OnetimeConfigInfoManager when wired
        self._pending_onetime: Dict[str, dict] = {}
        # queue_key -> pipeline, rebuilt lazily after every topology change
        self._queue_key_cache: Dict[int, CollectionPipeline] = {}
        # loongtenant bookkeeping -------------------------------------------
        # name -> reload generation (monotone per name; survives rollback)
        self._generations: Dict[str, int] = {}
        # old generations mid-drain: still live occupancy for the ledger's
        # quiesce probe even though the name already points at N+1
        self._draining: List[CollectionPipeline] = []
        # removals a chaos/control-plane fault deferred: retried at the
        # head of every subsequent update (the pipeline keeps serving in
        # the meantime — a deferred removal is never a loss)
        self._pending_removals: set = set()
        # name -> last reload outcome, for /debug/status tenants rows
        self._last_reload: Dict[str, dict] = {}
        global _active_manager
        _active_manager = self

    def update_pipelines(self, diff: ConfigDiff) -> None:
        self._mutate_topology(lambda: self._update_pipelines_inner(diff))

    def _mutate_topology(self, fn) -> None:
        """Run a topology mutation with the hot-path queue-key cache
        dropped for its duration (consumers fall back to the locked
        scan) and rebuilt at the end — lazy filling DURING the mutation
        window could cache a pipeline the mutation is replacing."""
        self._queue_key_cache = {}
        try:
            fn()
        finally:
            with self._lock:
                self._queue_key_cache = {
                    p.process_queue_key: p
                    for p in self._pipelines.values()}

    def _update_pipelines_inner(self, diff: ConfigDiff) -> None:
        with self._lock:
            deferred = sorted(self._pending_removals
                              - set(diff.added) - set(diff.modified))
        for name in list(diff.removed) + deferred:
            self._remove_pipeline(name)
        for name, cfg in list(diff.modified.items()) + list(diff.added.items()):
            if self._is_onetime(cfg) and self.onetime_manager is not None \
                    and self.onetime_manager.already_ran(cfg):
                log.info("onetime config %s already completed; skipping", name)
                continue
            self._apply_config(name, cfg)

    # -- removal -------------------------------------------------------------

    def _remove_pipeline(self, name: str) -> None:
        old = self._pipelines.get(name)
        if old is None:
            with self._lock:
                self._pending_removals.discard(name)
            return
        try:
            chaos.faultpoint(FP_UPDATE)
        except chaos.ChaosFault:
            # injected control-plane fault: the removal DEFERS — the
            # pipeline keeps serving (zero-loss beats promptness) and the
            # next update retries it
            with self._lock:
                self._pending_removals.add(name)
            log.warning("pipeline %s removal deferred (control-plane "
                        "fault); retrying on the next update", name)
            return
        old.stop(is_removing=True)
        old.release()
        if self.process_queue_manager is not None:
            self.process_queue_manager.delete_queue(old.process_queue_key)
        from ..ops import device_plane
        device_plane.unregister_tenant(name)
        with self._lock:
            del self._pipelines[name]
            self._generations.pop(name, None)
            self._last_reload.pop(name, None)
            self._pending_removals.discard(name)
        reload_metrics().counter("pipeline_removals_total").add(1)
        log.info("pipeline %s removed", name)

    # -- apply (add / modify) ------------------------------------------------

    def _apply_config(self, name: str, cfg: dict) -> bool:
        """Apply one config as generation N+1 with drain-and-handoff.
        Returns False on a failed init — generation N (if any) keeps
        serving, untouched."""
        t0 = time.perf_counter()
        with self._lock:
            # a config for this name REAPPEARING supersedes any deferred
            # removal, whether or not this apply succeeds — otherwise a
            # failed re-apply would roll back to the old generation only
            # for retry_pending_removals to stop it moments later
            self._pending_removals.discard(name)
        old = self._pipelines.get(name)
        gen = self._generations.get(name, 0) + 1
        p = CollectionPipeline()
        p.generation = gen
        try:
            # the control-plane fault point sits INSIDE the guarded apply:
            # an injected ERROR travels the exact rollback path a real
            # bad-config init failure does
            chaos.faultpoint(FP_UPDATE)
            ok = p.init(name, cfg, self.process_queue_manager,
                        self.sender_queue_manager,
                        reuse_queue_key=(old.process_queue_key
                                         if old else None))
        except Exception:  # noqa: BLE001 - a bad config must not kill the agent
            log.exception("pipeline %s generation %d init raised", name, gen)
            try:
                p.release()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                log.exception("release of failed generation %d raised", gen)
            ok = False
        if not ok:
            self._note_update_failed(name, gen, old, t0)
            return False
        # -- handoff: generation N+1 admits BEFORE N stops ------------------
        # sink side up first, then the name (and with it the shared queue
        # key) flips to the new generation: a worker popping the queue in
        # the very next instant walks the NEW chain into ready flushers
        p.start_flushers()
        if old is not None:
            # flush N's batched-but-unsent events BEFORE the flip: once
            # N+1 starts delivering, a partial batch still resident in
            # N's batcher would ship AFTER newer events of the same
            # source (batch residence can be seconds).  Groups still
            # IN-PROCESS in N's chain at the flip can still land behind
            # N+1's first sends on MinCnt>1 batched sinks — that residual
            # window is the concurrency the pause-free handoff buys and
            # is documented in docs/robustness.md; write-through sinks
            # (MinCnt=1) keep strict per-source order either way
            try:
                old.flush_batch()
            except Exception:  # noqa: BLE001 — a flush bug must not
                # block the handoff; the drain's final flush retries
                log.exception("pre-flip batch flush of %s failed", name)
        with self._lock:
            self._pipelines[name] = p
            self._generations[name] = gen
            if old is not None:
                # the old generation stays visible to live-occupancy
                # probes (ledger quiesce) until its drain completes
                self._draining.append(old)
        from ..ops import device_plane
        device_plane.register_tenant(name)
        drain_s = 0.0
        if old is not None:
            t_drain = time.perf_counter()
            try:
                self._drain_old_generation(old)
            finally:
                with self._lock:
                    self._draining.remove(old)
            drain_s = time.perf_counter() - t_drain
            drain_histogram().observe(drain_s)
        # inputs LAST: the old generation's tails closed during the drain,
        # so the new generation never double-reads a source
        p.start_inputs()
        dt = time.perf_counter() - t0
        reload_histogram().observe(dt)
        reload_metrics().counter("pipeline_reloads_total").add(1)
        flight.record("pipeline.reload", pipeline=name, generation=gen,
                      ms=round(dt * 1000.0, 3))
        with self._lock:
            self._last_reload[name] = {
                "generation": gen, "ok": True,
                "ms": round(dt * 1000.0, 3),
                "drain_ms": round(drain_s * 1000.0, 3)}
        log.info("pipeline %s generation %d %s in %.1f ms", name, gen,
                 "updated" if old else "started", dt * 1000.0)
        if self._is_onetime(cfg) and self.onetime_manager is not None:
            # ingestion finished inside start(), but completion is only
            # durable once the data has drained through the pipeline —
            # check_onetime_completion() marks it then
            self._pending_onetime[name] = cfg
        return True

    def _note_update_failed(self, name: str, gen: int,
                            old: Optional[CollectionPipeline],
                            t0: float) -> None:
        """Rollback: generation N keeps running exactly as it was.  The
        failure is alarmed once per (name, message), counted, and lands in
        the flight ring so a crash dump names the bad config."""
        reload_metrics().counter("config_update_failed_total").add(1)
        kept = (f"generation {gen - 1} keeps serving" if old is not None
                else "no previous generation to keep")
        AlarmManager.instance().send_alarm(
            AlarmType.CONFIG_UPDATE_FAILED,
            f"pipeline {name} generation {gen} failed to init; "
            f"rolled back ({kept})",
            AlarmLevel.ERROR, pipeline=name,
            details={"generation": str(gen),
                     "kept_old": str(old is not None)})
        flight.record("pipeline.reload_failed", pipeline=name,
                      generation=gen, kept_old=old is not None)
        with self._lock:
            self._last_reload[name] = {
                "generation": gen, "ok": False,
                "ms": round((time.perf_counter() - t0) * 1000.0, 3)}
        log.error("pipeline %s generation %d failed to init; %s",
                  name, gen, kept)

    def _drain_old_generation(self, old: CollectionPipeline) -> None:
        """Source-to-sink drain of generation N while N+1 already serves:
        inputs stop, in-process groups finish, held processor state and
        batchers flush through N's OWN chain, then N's global
        registrations release.  Payloads a wedged sink cannot drain within
        ``reload_drain_timeout`` spill to disk (enable_full_drain_mode) —
        the reload never blocks on a dead endpoint and never drops."""
        old.stop(is_removing=False)
        old.release()
        self._spill_wedged_queues(old)

    def _spill_wedged_queues(self, old: CollectionPipeline) -> None:
        # the import defines the enable_full_drain_mode flag (runner
        # module owns it) — read it only after
        from ..runner import flusher_runner as _fr
        fr = _fr._active_runner
        if fr is None or fr.disk_buffer is None \
                or not flags.get_flag("enable_full_drain_mode"):
            return
        queues = [f.plugin.sender_queue for f in old.flushers
                  if getattr(f.plugin, "sender_queue", None) is not None]
        if not queues:
            return
        deadline = time.monotonic() + max(
            0.0, float(flags.get_flag("reload_drain_timeout")))
        while any(not q.empty() for q in queues):
            if time.monotonic() < deadline:
                time.sleep(0.02)
                continue
            # deadline hit: spill whatever is claimable now, then give
            # items briefly in flight at the sink a few more rounds to
            # land back (or out) before giving up on them — an item the
            # rounds miss keeps retrying and exits through the normal
            # try-count spill
            spilled = 0
            for _ in range(10):
                for q in queues:
                    spilled += fr.spill_queue(q)
                if all(q.empty() for q in queues):
                    break
                time.sleep(0.05)
            if spilled:
                log.warning(
                    "reload drain timed out; spilled %d payloads of "
                    "retiring generation %d of %s to disk",
                    spilled, old.generation, old.name)
                flight.record("pipeline.reload_spill",
                              pipeline=old.name,
                              generation=old.generation, items=spilled)
            break

    def retry_pending_removals(self) -> None:
        """Drive chaos/control-plane-deferred removals to completion.
        Deferred removals normally retry at the head of the next
        update_pipelines call, but a QUIET config dir may never produce
        another diff — the application's supervision loop calls this
        each scan round (no-op when nothing is pending)."""
        with self._lock:
            pending = sorted(self._pending_removals)
        if not pending:
            return

        def _retry():
            for name in pending:
                self._remove_pipeline(name)
        self._mutate_topology(_retry)

    # -- onetime -------------------------------------------------------------

    def check_onetime_completion(self, process_queue_manager,
                                 sender_queue_manager=None) -> None:
        """Marks pending onetime configs done once their queues drained
        (at-least-once: a crash before this point re-runs the import)."""
        if not self._pending_onetime or self.onetime_manager is None:
            return
        for name, cfg in list(self._pending_onetime.items()):
            p = self.find_pipeline(name)
            if p is None:
                del self._pending_onetime[name]
                continue
            q = (process_queue_manager.get_queue(p.process_queue_key)
                 if process_queue_manager else None)
            if q is not None and not q.empty():
                continue
            if not p.wait_all_items_in_process_finished(timeout=0):
                continue
            p.flush_batch()
            if sender_queue_manager is not None and \
                    not sender_queue_manager.all_empty():
                continue
            self.onetime_manager.mark_done(cfg)
            del self._pending_onetime[name]
            log.info("onetime config %s completed and recorded", name)

    @staticmethod
    def _is_onetime(cfg: dict) -> bool:
        inputs = cfg.get("inputs", [])
        return bool(inputs) and all(
            str(i.get("Type", "")).endswith("_onetime") for i in inputs)

    # -- lookup --------------------------------------------------------------

    def find_pipeline(self, name: str) -> Optional[CollectionPipeline]:
        with self._lock:
            return self._pipelines.get(name)

    def generation_of(self, name: str) -> int:
        with self._lock:
            return self._generations.get(name, 0)

    def draining_pipelines(self) -> List[CollectionPipeline]:
        """Old generations currently mid-drain — still live occupancy for
        the conservation auditor even though the name already resolves to
        the next generation."""
        with self._lock:
            return list(self._draining)

    def find_pipeline_by_queue_key(self, key: int) -> Optional[CollectionPipeline]:
        # hot path: the processor runner resolves this once per popped
        # group — a cached key map beats scanning pipelines under the lock
        p = self._queue_key_cache.get(key)
        if p is not None:
            return p
        # miss (mid-update window): scan, but do NOT write the cache — a
        # lazy fill here could pin a pipeline that the in-flight update is
        # about to stop; update_pipelines rebuilds the map when it's done
        with self._lock:
            for p in self._pipelines.values():
                if p.process_queue_key == key:
                    return p
        return None

    def pipeline_names(self) -> List[str]:
        with self._lock:
            return list(self._pipelines)

    def tenants_status(self) -> dict:
        """The /debug/status ``tenants`` section: per-pipeline generation,
        queue depth, last reload outcome and device-budget share — the
        one-page answer to "which tenants does this agent run and how did
        their last reload go" (observe-only, fail-soft)."""
        from ..ops import device_plane
        shares = device_plane.tenant_snapshot()
        pqm = self.process_queue_manager
        with self._lock:
            items = list(self._pipelines.items())
            generations = dict(self._generations)
            last = {n: dict(r) for n, r in self._last_reload.items()}
            draining = [(p.name, p.generation) for p in self._draining]
            pending_removals = sorted(self._pending_removals)
        tenants = {}
        for name, p in items:
            row = {"generation": generations.get(name, p.generation),
                   "queue_key": p.process_queue_key}
            if pqm is not None:
                q = pqm.get_queue(p.process_queue_key)
                if q is not None:
                    row["queue_depth"] = q.size()
            if name in last:
                row["last_reload"] = last[name]
            if name in shares:
                row["device_budget"] = shares[name]
            tenants[name] = row
        doc = {"count": len(tenants), "tenants": tenants}
        if draining:
            doc["draining"] = [{"pipeline": n, "generation": g}
                               for n, g in draining]
        if pending_removals:
            doc["pending_removals"] = pending_removals
        return doc

    def stop_all(self) -> None:
        global _active_manager
        if _active_manager is self:
            _active_manager = None
        with self._lock:
            pipelines = list(self._pipelines.values())
            names = list(self._pipelines)
        for p in pipelines:
            p.stop(is_removing=False)
        # release the device-budget shares too: a stopped manager's names
        # must not linger in the module-level registry and shrink every
        # later manager's per-tenant share (tests/benches build and
        # discard managers freely)
        from ..ops import device_plane
        for name in names:
            device_plane.unregister_tenant(name)

    def flush_all_batch(self) -> None:
        with self._lock:
            pipelines = list(self._pipelines.values())
        for p in pipelines:
            p.flush_batch()
