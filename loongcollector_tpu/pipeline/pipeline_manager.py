"""Pipeline manager: named pipelines + atomic hot swap.

Reference: core/collection_pipeline/CollectionPipelineManager.cpp
UpdatePipelines(diff) — per changed pipeline: stop old (drain), init + start
new; removed pipelines stop with is_removing=True and their queues are GC'd.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils.logger import get_logger
from .pipeline import CollectionPipeline

log = get_logger("pipeline_manager")

# observe-only handle for /debug/status (monitor/exposition.py): the most
# recently constructed manager — never constructed, never mutated through
# this; stop_all() clears it (runner/processor_runner.py idiom)
_active_manager = None


class ConfigDiff:
    def __init__(self) -> None:
        self.added: Dict[str, dict] = {}
        self.modified: Dict[str, dict] = {}
        self.removed: List[str] = []

    def empty(self) -> bool:
        return not (self.added or self.modified or self.removed)


class CollectionPipelineManager:
    def __init__(self, process_queue_manager=None, sender_queue_manager=None):
        self._pipelines: Dict[str, CollectionPipeline] = {}
        self._lock = threading.Lock()
        self.process_queue_manager = process_queue_manager
        self.sender_queue_manager = sender_queue_manager
        self.onetime_manager = None  # OnetimeConfigInfoManager when wired
        self._pending_onetime: Dict[str, dict] = {}
        # queue_key -> pipeline, rebuilt lazily after every topology change
        self._queue_key_cache: Dict[int, CollectionPipeline] = {}
        global _active_manager
        _active_manager = self

    def update_pipelines(self, diff: ConfigDiff) -> None:
        # drop the hot-path queue-key cache for the duration of the update
        # (consumers fall back to the locked scan) and rebuild it at the
        # end — lazy filling DURING the mutation window could cache a
        # pipeline this very update is replacing
        self._queue_key_cache = {}
        try:
            self._update_pipelines_inner(diff)
        finally:
            with self._lock:
                self._queue_key_cache = {
                    p.process_queue_key: p
                    for p in self._pipelines.values()}

    def _update_pipelines_inner(self, diff: ConfigDiff) -> None:
        for name in diff.removed:
            old = self._pipelines.get(name)
            if old is not None:
                old.stop(is_removing=True)
                old.release()
                if self.process_queue_manager is not None:
                    self.process_queue_manager.delete_queue(old.process_queue_key)
                with self._lock:
                    del self._pipelines[name]
                log.info("pipeline %s removed", name)
        for name, cfg in list(diff.modified.items()) + list(diff.added.items()):
            if self._is_onetime(cfg) and self.onetime_manager is not None \
                    and self.onetime_manager.already_ran(cfg):
                log.info("onetime config %s already completed; skipping", name)
                continue
            old = self._pipelines.get(name)
            if old is not None:
                old.stop(is_removing=False)
                old.release()
            p = CollectionPipeline()
            try:
                ok = p.init(name, cfg, self.process_queue_manager,
                            self.sender_queue_manager,
                            reuse_queue_key=(old.process_queue_key
                                             if old else None))
            except Exception:  # noqa: BLE001 - a bad config must not kill the agent
                log.exception("pipeline %s init raised", name)
                p.release()
                ok = False
            if not ok:
                log.error("pipeline %s failed to init; keeping none", name)
                with self._lock:
                    self._pipelines.pop(name, None)
                continue
            # register BEFORE starting inputs (sink-to-source: the runner must
            # be able to resolve the queue key as soon as data flows)
            with self._lock:
                self._pipelines[name] = p
            p.start()
            log.info("pipeline %s %s", name, "updated" if old else "started")
            if self._is_onetime(cfg) and self.onetime_manager is not None:
                # ingestion finished inside start(), but completion is only
                # durable once the data has drained through the pipeline —
                # check_onetime_completion() marks it then
                self._pending_onetime[name] = cfg

    def check_onetime_completion(self, process_queue_manager,
                                 sender_queue_manager=None) -> None:
        """Marks pending onetime configs done once their queues drained
        (at-least-once: a crash before this point re-runs the import)."""
        if not self._pending_onetime or self.onetime_manager is None:
            return
        for name, cfg in list(self._pending_onetime.items()):
            p = self.find_pipeline(name)
            if p is None:
                del self._pending_onetime[name]
                continue
            q = (process_queue_manager.get_queue(p.process_queue_key)
                 if process_queue_manager else None)
            if q is not None and not q.empty():
                continue
            if not p.wait_all_items_in_process_finished(timeout=0):
                continue
            p.flush_batch()
            if sender_queue_manager is not None and \
                    not sender_queue_manager.all_empty():
                continue
            self.onetime_manager.mark_done(cfg)
            del self._pending_onetime[name]
            log.info("onetime config %s completed and recorded", name)

    @staticmethod
    def _is_onetime(cfg: dict) -> bool:
        inputs = cfg.get("inputs", [])
        return bool(inputs) and all(
            str(i.get("Type", "")).endswith("_onetime") for i in inputs)

    def find_pipeline(self, name: str) -> Optional[CollectionPipeline]:
        with self._lock:
            return self._pipelines.get(name)

    def find_pipeline_by_queue_key(self, key: int) -> Optional[CollectionPipeline]:
        # hot path: the processor runner resolves this once per popped
        # group — a cached key map beats scanning pipelines under the lock
        p = self._queue_key_cache.get(key)
        if p is not None:
            return p
        # miss (mid-update window): scan, but do NOT write the cache — a
        # lazy fill here could pin a pipeline that the in-flight update is
        # about to stop; update_pipelines rebuilds the map when it's done
        with self._lock:
            for p in self._pipelines.values():
                if p.process_queue_key == key:
                    return p
        return None

    def pipeline_names(self) -> List[str]:
        with self._lock:
            return list(self._pipelines)

    def stop_all(self) -> None:
        global _active_manager
        if _active_manager is self:
            _active_manager = None
        with self._lock:
            pipelines = list(self._pipelines.values())
        for p in pipelines:
            p.stop(is_removing=False)

    def flush_all_batch(self) -> None:
        with self._lock:
            pipelines = list(self._pipelines.values())
        for p in pipelines:
            p.flush_batch()
