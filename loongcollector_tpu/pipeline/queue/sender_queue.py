"""Sender queues: serialized payloads awaiting network dispatch.

Reference: core/collection_pipeline/queue/SenderQueue*.cpp and
SenderQueueItem.h — per-flusher bounded queues of compressed payloads with
retry state; GetAvailableItems consults per-destination rate and AIMD
concurrency limiters (SenderQueueManager.cpp:112-135); draining feeds back
to process queues.
"""

from __future__ import annotations

import enum
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .limiter import ConcurrencyLimiter, RateLimiter


class SendingStatus(enum.Enum):
    IDLE = 0
    SENDING = 1


class SenderQueueItem:
    __slots__ = ("data", "raw_size", "flusher", "queue_key", "status",
                 "enqueue_time", "try_count", "last_send_time", "tag",
                 "in_flight", "event_cnt", "spans", "stamps")

    def __init__(self, data: bytes, raw_size: int, flusher=None,
                 queue_key: int = 0, tag: Optional[dict] = None,
                 event_cnt: int = 0, spans: tuple = (),
                 stamps: tuple = ()):
        self.data = data
        self.raw_size = raw_size
        self.flusher = flusher
        self.queue_key = queue_key
        self.status = SendingStatus.IDLE
        self.enqueue_time = time.monotonic()
        self.try_count = 0
        self.last_send_time = 0.0
        self.tag = tag or {}
        self.in_flight = False
        # loongledger: how many events this payload carries — serialization
        # erases event identity, so the count rides the item to keep the
        # send_ok/spill boundaries in event units (0 = unknown provenance,
        # e.g. a pre-ledger disk-buffer file; ledgers as 0 on both sides)
        self.event_cnt = event_cnt
        # loongcrash: SOURCE (dev, inode, offset, length) spans this payload
        # carries — the terminal boundary (send_ok / durable spill / tagged
        # drop) acks them into the checkpoint watermark; () = no file
        # provenance (http input, replay) and nothing to ack
        self.spans = spans
        # loongslo: ingest stamps (monotonic ns) of the groups serialized
        # into this payload — the same terminal boundary observes their
        # ingest→terminal sojourn; () = stampless (plane off, replay)
        self.stamps = stamps


class SenderQueue:
    def __init__(self, key: int, capacity: int = 10, pipeline_name: str = ""):
        self.key = key
        self.pipeline_name = pipeline_name
        self._cap_high = max(capacity, 1)
        self._cap_low = max(int(capacity * 2 / 3), 1)
        self._items: Deque[SenderQueueItem] = deque()
        self._lock = threading.Lock()
        self._valid_to_push = True
        self._retired = False
        self._feedback = []
        # backlog-aware hand-off (loongcolumn): the manager installs its
        # data event here so a push wakes the FlusherRunner immediately
        # instead of waiting out its poll interval
        self._on_push = None
        self.rate_limiter: Optional[RateLimiter] = None
        self.concurrency_limiters: List[ConcurrencyLimiter] = []
        self.total_pushed = 0
        self.total_removed = 0

    def push(self, item: SenderQueueItem) -> bool:
        with self._lock:
            if self._retired:
                # deleted queue: a stale-reference push (e.g. a timeout
                # flush driving a removed pipeline's batcher mid-hot-
                # reload) would strand the payload in an orphaned queue
                # nothing dispatches, counts, or ledgers — refuse it,
                # matching BoundedProcessQueue.retire()'s push gate.
                # False means the CALLER still owns the payload (disk-
                # buffer replay keeps its file; flush paths record the
                # terminal drop) — recording here would double-terminate
                # a refused replay whose spill file survives.
                return False
            # Sender queues accept beyond the watermark (data already
            # left the process stage and must not be lost); validity
            # flag throttles the upstream instead (reference
            # BoundedSenderQueueInterface).
            self._items.append(item)
            self.total_pushed += 1
            if len(self._items) >= self._cap_high:
                self._valid_to_push = False
            notify = self._on_push
        if notify is not None:
            notify()        # outside the lock: wake the FlusherRunner
        return True

    def is_valid_to_push(self) -> bool:
        with self._lock:
            return self._valid_to_push

    def get_available_items(self, limit: int) -> List[SenderQueueItem]:
        out: List[SenderQueueItem] = []
        with self._lock:
            if self._retired:
                # deleted queue (loongledger): its remaining IDLE items
                # were already counted drop(queue_deleted) — dispatching
                # one now would give the same payload two terminals
                return out
            for item in self._items:
                if len(out) >= limit:
                    break
                if item.status is not SendingStatus.IDLE:
                    continue
                if self.rate_limiter and not self.rate_limiter.is_valid_to_pop():
                    break
                if any(not cl.is_valid_to_pop() for cl in self.concurrency_limiters):
                    break
                item.status = SendingStatus.SENDING
                item.try_count += 1
                item.last_send_time = time.monotonic()
                if self.rate_limiter:
                    self.rate_limiter.post_pop(len(item.data))
                for cl in self.concurrency_limiters:
                    cl.post_pop()
                out.append(item)
        return out

    def claim_idle_items(self) -> List[SenderQueueItem]:
        """Atomically claim every IDLE, not-in-flight item (status →
        SENDING so the dispatch loop skips them).  The caller owns the
        claimed items' terminal outcome — spill them or hand each back
        via reset_item_status.  Used by the hot-reload drain spill
        (loongtenant); keeps _items/_lock private to this class."""
        out: List[SenderQueueItem] = []
        with self._lock:
            for item in self._items:
                if item.status is SendingStatus.IDLE \
                        and not item.in_flight:
                    item.status = SendingStatus.SENDING
                    out.append(item)
        return out

    def remove(self, item: SenderQueueItem) -> bool:
        feedbacks = []
        with self._lock:
            try:
                self._items.remove(item)
            except ValueError:
                return False
            self.total_removed += 1
            if not self._valid_to_push and len(self._items) <= self._cap_low:
                self._valid_to_push = True
                feedbacks = list(self._feedback)
        for fb in feedbacks:
            fb.feedback(self.key)
        return True

    def reset_item_status(self, item: SenderQueueItem) -> None:
        with self._lock:
            item.status = SendingStatus.IDLE

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def oldest_age(self) -> Optional[float]:
        """Seconds the oldest queued payload has waited (None when empty)
        — the ``sender_queue_lag_seconds`` watermark (loongledger)."""
        with self._lock:
            if not self._items:
                return None
            return max(0.0, time.monotonic() - self._items[0].enqueue_time)

    def empty(self) -> bool:
        with self._lock:
            return not self._items

    def set_feedback(self, *feedbacks) -> None:
        with self._lock:
            self._feedback = list(feedbacks)


class SenderQueueManager:
    def __init__(self) -> None:
        self._queues: Dict[int, SenderQueue] = {}
        self._marked: set = set()
        self._lock = threading.Lock()
        # backlog-aware hand-off: pushes set this event; the FlusherRunner
        # waits on it instead of sleeping out a fixed poll interval
        self._data_event = threading.Event()

    def wait_for_data(self, timeout: float) -> bool:
        """Block until a push signals new payloads (or timeout — the
        deadline fallback that keeps retry/replay cadences alive)."""
        if self._data_event.wait(timeout):
            self._data_event.clear()
            return True
        return False

    def mark_for_deletion(self, key: int) -> None:
        """Queue is deleted once its in-flight items drain (reference
        SenderQueueManager GC semantics — data already serialized must not
        be lost on pipeline swap)."""
        with self._lock:
            self._marked.add(key)

    def gc_marked(self) -> None:
        with self._lock:
            for key in list(self._marked):
                q = self._queues.get(key)
                if q is None or q.empty():
                    self._queues.pop(key, None)
                    self._marked.discard(key)

    def create_or_reuse_queue(self, key: int, capacity: int = 10,
                              pipeline_name: str = "") -> SenderQueue:
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                q = SenderQueue(key, capacity, pipeline_name)
                q._on_push = self._data_event.set
                self._queues[key] = q
            return q

    def get_queue(self, key: int) -> Optional[SenderQueue]:
        with self._lock:
            return self._queues.get(key)

    def delete_queue(self, key: int) -> None:
        with self._lock:
            q = self._queues.pop(key, None)
        if q is not None:
            from ...monitor import ledger, slo
            # serialized payloads still queued die with their queue
            # (direct delete, not the drain-then-GC path): terminal.
            # SENDING items are skipped — their delivery callback is
            # still coming and ledgers the terminal outcome (send_ok /
            # drop / retry_orphaned); counting them here too would
            # double-terminate the same events.  The retired flag is
            # raised under the SAME lock the dead snapshot is taken
            # under — and unconditionally, so a FlusherRunner iterating
            # a stale queue list cannot dispatch from a deleted queue
            # whether or not the ledger is counting
            led = ledger.is_on()
            slo_on = slo.is_on()
            with q._lock:
                q._retired = True
                dead = ([(i.event_cnt, len(i.data), i.stamps)
                         for i in q._items
                         if i.status is SendingStatus.IDLE]
                        if (led or slo_on) else [])
            for events, nbytes, stamps in dead:
                if led:
                    ledger.record(q.pipeline_name, ledger.B_DROP,
                                  events, nbytes, tag="queue_deleted")
                if slo_on:
                    slo.observe_stamps(q.pipeline_name, stamps,
                                       slo.OUTCOME_DROP)

    def get_available_items(self, limit_per_queue: int = 10
                            ) -> List[SenderQueueItem]:
        with self._lock:
            queues = list(self._queues.values())
        out: List[SenderQueueItem] = []
        for q in queues:
            out.extend(q.get_available_items(limit_per_queue))
        return out

    def remove_item(self, item: SenderQueueItem) -> bool:
        q = self.get_queue(item.queue_key)
        return q.remove(item) if q else False

    def all_empty(self) -> bool:
        with self._lock:
            queues = list(self._queues.values())
        return all(q.empty() for q in queues)
