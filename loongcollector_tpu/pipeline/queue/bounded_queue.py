"""Bounded process queues with high/low watermark back-pressure.

Reference: core/collection_pipeline/queue/BoundedProcessQueue.cpp:34,53,89-93
and QueueParam.h:23-33 (high watermark = capacity, low = cap*2/3 by default).
Push fails above the high watermark; popping below the low watermark fires the
upstream FeedbackInterface so blocked inputs resume — the same contract the
TPU device queue honours (SURVEY.md §5.8: the host↔device boundary lives
behind these watermarks).

CircularProcessQueue (drop-oldest) serves streaming inputs that must never
block the producer (eBPF perf buffers, Prometheus streams — reference
queue/CircularProcessQueue.cpp).
"""

from __future__ import annotations

import enum
import threading
import time
from collections import deque
from typing import Callable, Deque, List, Optional, Tuple

from ... import chaos
from ...models import PipelineEventGroup
from ...monitor import ledger

DEFAULT_CAPACITY = 20
LOW_WATERMARK_RATIO = 2 / 3

# loongcolumn backlog-aware hand-off: the queue is bounded in BYTES as well
# as groups.  A count-only bound lets large groups (512 KB reader chunks)
# pile up ~15 MB of backlog, and at a few ms service time per group that IS
# the 131 ms queue_wait plateau BENCH_r08 recorded — every group waited
# capacity x service_time regardless of load.  The byte watermark keeps the
# standing backlog shallow (the producer feedback-blocks earlier), so
# queue_wait tracks the actual service rate; the count bound still guards
# the many-tiny-groups shape.  0 disables the byte bound.
DEFAULT_MAX_BYTES = 2 * 1024 * 1024

FP_PUSH = chaos.register_point("bounded_queue.push")

# shared queue-wait histogram (lazy: importing queues never touches the
# metrics registry); every bounded process queue observes into it
_wait_hist = None


def queue_wait_histogram():
    global _wait_hist
    if _wait_hist is None:
        from ...monitor.metrics import shared_histogram
        _wait_hist = shared_histogram("queue_wait_seconds",
                                      labels={"component": "process_queue"})
    return _wait_hist


class QueueStatus(enum.Enum):
    OK = 0
    FULL = 1
    EMPTY = 2


class FeedbackInterface:
    """Upstream wakeup hook (reference queue/FeedbackInterface.h)."""

    def feedback(self, key: int) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class BoundedProcessQueue:
    """Count-bounded MPSC queue with watermark feedback.

    Thread-safe; producers are input threads, the consumer is a processor
    runner.  `set_pop_enabled(False)` supports the drain-before-stop pipeline
    swap semantics (reference CollectionPipeline.cpp:659-677).
    """

    def __init__(self, key: int, priority: int = 1,
                 capacity: int = DEFAULT_CAPACITY,
                 pipeline_name: str = "",
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.key = key
        self.priority = priority
        self.pipeline_name = pipeline_name
        self._cap_high = max(capacity, 1)
        self._cap_low = max(int(capacity * LOW_WATERMARK_RATIO), 1)
        self._bytes_high = max(int(max_bytes), 0)       # 0 = unbounded
        self._bytes_low = int(self._bytes_high * LOW_WATERMARK_RATIO)
        self._bytes = 0
        self._items: Deque[PipelineEventGroup] = deque()
        # enqueue timestamps + sizes ride parallel FIFOs (groups use
        # __slots__, so neither can be stamped on the group itself)
        self._enq_ts: Deque[float] = deque()
        self._sizes: Deque[int] = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._valid_to_push = True
        self._pop_enabled = True
        self._retired = False
        self._feedback: List[FeedbackInterface] = []
        # metrics
        self.total_pushed = 0
        self.total_popped = 0
        self.total_rejected = 0

    # -- producer side ------------------------------------------------------

    def _over_high(self) -> bool:
        """High-watermark predicate (lock held): groups OR bytes."""
        if len(self._items) >= self._cap_high:
            return True
        return bool(self._bytes_high) and self._bytes >= self._bytes_high

    def _under_low(self) -> bool:
        """Low-watermark predicate (lock held): both bounds must clear
        before the upstream feedback fires."""
        if len(self._items) > self._cap_low:
            return False
        return not self._bytes_high or self._bytes <= self._bytes_low

    def push(self, group: PipelineEventGroup) -> bool:
        # an exception cannot propagate to input threads, so an injected
        # "error" degrades in this queue's own vocabulary: a watermark-style
        # rejection the producer already handles with feedback-blocking
        decision = chaos.faultpoint(FP_PUSH, raise_=False)
        if decision is not None and decision.action == chaos.ACTION_ERROR:
            with self._lock:
                self.total_rejected += 1
            return False
        # computed outside the lock, and ONLY when someone consumes it
        # (byte watermark or ledger): data_size() is O(events) on
        # materialized row groups
        size = group.data_size() if (self._bytes_high or ledger.is_on()) \
            else 0
        with self._lock:
            if self._retired or not self._valid_to_push:
                self.total_rejected += 1
                return False
            self._items.append(group)
            self._enq_ts.append(time.perf_counter())
            self._sizes.append(size)
            self._bytes += size
            self.total_pushed += 1
            if self._over_high():
                self._valid_to_push = False
            self._not_empty.notify()
        # loongledger: queue admit == enqueue boundary (outside the lock —
        # the ledger takes its own short lock)
        if ledger.is_on():
            ledger.record(self.pipeline_name, ledger.B_ENQUEUE,
                          len(group), size)
        return True

    def is_valid_to_push(self) -> bool:
        with self._lock:
            return self._valid_to_push

    # -- consumer side ------------------------------------------------------

    def _pop_locked(self) -> Tuple[PipelineEventGroup, Optional[float]]:
        """One popleft with its byte/timestamp bookkeeping (lock held)."""
        item = self._items.popleft()
        enq = self._enq_ts.popleft() if self._enq_ts else None
        if self._sizes:
            self._bytes -= self._sizes.popleft()
        self.total_popped += 1
        return item, enq

    def pop(self) -> Optional[PipelineEventGroup]:
        with self._lock:
            if not self._pop_enabled or not self._items:
                return None
            item, enq = self._pop_locked()
            if not self._valid_to_push and self._under_low():
                self._valid_to_push = True
                feedbacks = list(self._feedback)
            else:
                feedbacks = []
        if enq is not None:
            queue_wait_histogram().observe(time.perf_counter() - enq)
        if ledger.is_on():
            ledger.record(self.pipeline_name, ledger.B_DEQUEUE,
                          len(item), item.data_size())
        for fb in feedbacks:
            fb.feedback(self.key)
        return item

    def pop_run(self, max_groups: int, max_bytes: int
                ) -> List[PipelineEventGroup]:
        """Backlog-aware pop (loongcolumn): drain up to ``max_groups`` /
        ``max_bytes`` of queued groups in ONE lock acquisition.  The run
        length follows occupancy — a trickle pops one group exactly like
        pop(), a backlog amortises the per-pop hand-off (lock, CV, ledger,
        dispatch) across the whole run.  Per-group queue_wait attribution
        is preserved."""
        now = None
        waits: List[float] = []
        out: List[PipelineEventGroup] = []
        nbytes = 0
        with self._lock:
            if not self._pop_enabled:
                return out
            while self._items and len(out) < max_groups:
                if out and nbytes + (self._sizes[0] if self._sizes else 0) \
                        > max_bytes:
                    break
                size = self._sizes[0] if self._sizes else 0
                item, enq = self._pop_locked()
                nbytes += size
                out.append(item)
                if enq is not None:
                    if now is None:
                        now = time.perf_counter()
                    waits.append(now - enq)
            if out and not self._valid_to_push and self._under_low():
                self._valid_to_push = True
                feedbacks = list(self._feedback)
            else:
                feedbacks = []
        if waits:
            hist = queue_wait_histogram()
            for w in waits:
                hist.observe(w)
        if out and ledger.is_on():
            ledger.record(self.pipeline_name, ledger.B_DEQUEUE,
                          sum(len(g) for g in out), nbytes)
        for fb in feedbacks:
            fb.feedback(self.key)
        return out

    def oldest_age(self) -> Optional[float]:
        """Seconds the oldest queued group has waited (None when empty) —
        the per-pipeline ``queue_lag_seconds`` watermark (loongledger)."""
        with self._lock:
            if not self._enq_ts:
                return None
            return time.perf_counter() - self._enq_ts[0]

    def set_pop_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._pop_enabled = enabled

    def retire(self) -> None:
        """Deleted-queue gate (loongledger): refuse new pushes and stop
        pops, under the same lock both check, so delete_queue's terminal
        accounting of the remaining groups is the last word — a racing
        push rolls back unledgered, a racing pop cannot re-terminate a
        group already counted dead."""
        with self._lock:
            self._retired = True
            self._pop_enabled = False

    def empty(self) -> bool:
        with self._lock:
            return not self._items

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def bytes_queued(self) -> int:
        with self._lock:
            return self._bytes

    def set_feedback(self, *feedbacks: FeedbackInterface) -> None:
        with self._lock:
            self._feedback = list(feedbacks)


class CircularProcessQueue(BoundedProcessQueue):
    """Drop-oldest variant: push never fails; over capacity the oldest group
    is discarded (reference queue/CircularProcessQueue.cpp)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.total_dropped = 0

    def push(self, group: PipelineEventGroup) -> bool:
        evicted = []
        size = group.data_size() if (self._bytes_high or ledger.is_on()) \
            else 0
        with self._lock:
            if self._retired:      # deleted queue: roll back, unledgered
                return False
            self._items.append(group)
            self._enq_ts.append(time.perf_counter())
            self._sizes.append(size)
            self._bytes += size
            self.total_pushed += 1
            # drop-oldest on EITHER bound: circular queues never block the
            # producer, so the byte watermark evicts instead of refusing
            # (len > 1 guard: one oversized group must still ship)
            while len(self._items) > self._cap_high or (
                    self._bytes_high and self._bytes > self._bytes_high
                    and len(self._items) > 1):
                evicted.append(self._items.popleft())
                if self._enq_ts:
                    self._enq_ts.popleft()
                if self._sizes:
                    self._bytes -= self._sizes.popleft()
                self.total_dropped += 1
            self._not_empty.notify()
        if ledger.is_on():
            ledger.record(self.pipeline_name, ledger.B_ENQUEUE,
                          len(group), size)
            # drop-oldest shedding is a terminal discard: ledgered with a
            # reason so the conservation residual stays zero by design
            for old in evicted:
                ledger.record(self.pipeline_name, ledger.B_DROP,
                              len(old), old.data_size(), tag="circular_evict")
        return True

    def is_valid_to_push(self) -> bool:
        return True
