"""Process-queue manager: per-pipeline queues, 3 priorities, round-robin pop.

Reference: core/collection_pipeline/queue/ProcessQueueManager.{h,cpp}
(PushQueue :148, priorities + round-robin within priority :45,91).  The
consumer side blocks on a shared condition until any queue has data.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ...models import PipelineEventGroup
from .bounded_queue import BoundedProcessQueue, CircularProcessQueue

PRIORITY_COUNT = 3  # 0 = highest


class ProcessQueueManager:
    def __init__(self) -> None:
        self._queues: Dict[int, BoundedProcessQueue] = {}
        self._lock = threading.Lock()
        self._data_cv = threading.Condition(self._lock)
        self._rr_cursor: Dict[int, int] = {p: 0 for p in range(PRIORITY_COUNT)}
        # pop hot path: per-priority queue lists are rebuilt only when the
        # topology changes (one pop per processed group made the per-pop
        # snapshot copies measurable)
        self._version = 0
        self._snapshot_version = -1
        self._by_prio: Dict[int, list] = {}

    # -- lifecycle ----------------------------------------------------------

    def create_or_reuse_queue(self, key: int, priority: int = 1,
                              capacity: int = 20, pipeline_name: str = "",
                              circular: bool = False) -> BoundedProcessQueue:
        with self._lock:
            q = self._queues.get(key)
            if q is None or isinstance(q, CircularProcessQueue) != circular:
                cls = CircularProcessQueue if circular else BoundedProcessQueue
                q = cls(key, priority, capacity, pipeline_name)
                q._manager_cv = self._data_cv
                self._queues[key] = q
                self._version += 1
            return q

    def delete_queue(self, key: int) -> None:
        with self._lock:
            if self._queues.pop(key, None) is not None:
                self._version += 1

    def get_queue(self, key: int) -> Optional[BoundedProcessQueue]:
        with self._lock:
            return self._queues.get(key)

    # -- producer -----------------------------------------------------------

    def push_queue(self, key: int, group: PipelineEventGroup) -> bool:
        with self._lock:
            q = self._queues.get(key)
        if q is None:
            return False
        pushed = q.push(group)
        if pushed:
            with self._data_cv:
                self._data_cv.notify()
        return pushed

    def is_valid_to_push(self, key: int) -> bool:
        q = self.get_queue(key)
        return q is not None and q.is_valid_to_push()

    # -- consumer -----------------------------------------------------------

    def pop_item(self, timeout: float = 0.2
                 ) -> Optional[Tuple[int, PipelineEventGroup]]:
        """Priority-ordered, round-robin within each priority level
        (reference ProcessQueueManager.h:91)."""
        item = self._try_pop()
        if item is not None:
            return item
        with self._data_cv:
            self._data_cv.wait(timeout)
        return self._try_pop()

    def _try_pop(self) -> Optional[Tuple[int, PipelineEventGroup]]:
        with self._lock:
            if self._snapshot_version != self._version:
                self._by_prio = {p: [] for p in range(PRIORITY_COUNT)}
                for q in self._queues.values():
                    # KeyError here = misconfigured priority; silently
                    # parking the queue in an unvisited bucket would stall
                    # its data instead
                    self._by_prio[q.priority].append(q)
                self._snapshot_version = self._version
            by_prio = self._by_prio
            cursors = dict(self._rr_cursor)
        for prio in range(PRIORITY_COUNT):
            level = by_prio.get(prio)
            if not level:
                continue
            start = cursors.get(prio, 0) % len(level)
            for i in range(len(level)):
                q = level[(start + i) % len(level)]
                group = q.pop()
                if group is not None:
                    with self._lock:
                        self._rr_cursor[prio] = (start + i + 1) % len(level)
                    return q.key, group
        return None

    def all_empty(self) -> bool:
        with self._lock:
            queues = list(self._queues.values())
        return all(q.empty() for q in queues)

    def wake_up(self) -> None:
        with self._data_cv:
            self._data_cv.notify_all()

    def queue_names(self):
        with self._lock:
            return {k: q.pipeline_name for k, q in self._queues.items()}
