"""Process-queue manager: per-pipeline queues, 3 priorities, round-robin pop.

Reference: core/collection_pipeline/queue/ProcessQueueManager.{h,cpp}
(PushQueue :148, priorities + round-robin within priority :45,91).  The
consumer side blocks on a shared condition until any queue has data.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ...models import PipelineEventGroup
from ...monitor import ledger, slo
from .bounded_queue import (DEFAULT_MAX_BYTES, BoundedProcessQueue,
                            CircularProcessQueue)

PRIORITY_COUNT = 3  # 0 = highest

# loongcolumn backlog-aware pop: default caps for one consumer run — a
# trickle still pops single groups; a backlog hands the worker several
# groups per lock/dispatch cycle (runner amortises the per-group hand-off)
RUN_MAX_GROUPS = 8
RUN_MAX_BYTES = 4 * 1024 * 1024


class ProcessQueueManager:
    def __init__(self) -> None:
        self._queues: Dict[int, BoundedProcessQueue] = {}
        self._lock = threading.Lock()
        self._data_cv = threading.Condition(self._lock)
        self._rr_cursor: Dict[int, int] = {p: 0 for p in range(PRIORITY_COUNT)}
        # pop hot path: per-priority queue lists are rebuilt only when the
        # topology changes (one pop per processed group made the per-pop
        # snapshot copies measurable)
        self._version = 0
        self._snapshot_version = -1
        self._by_prio: Dict[int, list] = {}
        # loongledger: deleted-key → pipeline-name tombstones so a group
        # popped just before a hot-reload delete still attributes its drop
        # to the pipeline that ingested it (bounded; see delete_queue)
        self._retired_names: Dict[int, str] = {}

    # -- lifecycle ----------------------------------------------------------

    def create_or_reuse_queue(self, key: int, priority: int = 1,
                              capacity: int = 20, pipeline_name: str = "",
                              circular: bool = False,
                              max_bytes: int = DEFAULT_MAX_BYTES
                              ) -> BoundedProcessQueue:
        with self._lock:
            self._retired_names.pop(key, None)   # key is live again
            q = self._queues.get(key)
            if q is None or isinstance(q, CircularProcessQueue) != circular:
                cls = CircularProcessQueue if circular else BoundedProcessQueue
                q = cls(key, priority, capacity, pipeline_name,
                        max_bytes=max_bytes)
                q._manager_cv = self._data_cv
                self._queues[key] = q
                self._version += 1
            return q

    def delete_queue(self, key: int) -> None:
        with self._lock:
            q = self._queues.pop(key, None)
            if q is not None:
                self._version += 1
                self._retired_names[key] = q.pipeline_name
                while len(self._retired_names) > 1024:   # churn bound
                    self._retired_names.pop(next(iter(self._retired_names)))
        if q is None:
            return
        # the queue retires unconditionally (same lock push()/pop() check):
        # an input thread holding a stale reference must have its push
        # REFUSED — with or without the ledger, a group admitted into an
        # orphaned queue object no worker polls is a silent loss
        q.retire()
        led = ledger.is_on()
        slo_on = slo.is_on()
        if led or slo_on:
            # groups still queued die with their queue (pipeline removed
            # without drain): a terminal, reason-tagged discard.  retire()
            # ran first, so a worker holding a stale priority snapshot
            # cannot pop a group after we count it dead (two terminals)
            with q._lock:
                dead = list(q._items)
            if led:
                for g in dead:
                    ledger.record(q.pipeline_name, ledger.B_DROP,
                                  len(g), g.data_size(), tag="queue_deleted")
            if slo_on:
                # their stamps terminate here too, or the dead pipeline's
                # freshness watermark would age forever
                slo.observe_groups(q.pipeline_name, dead, slo.OUTCOME_DROP)

    def get_queue(self, key: int) -> Optional[BoundedProcessQueue]:
        with self._lock:
            return self._queues.get(key)

    def retired_pipeline_name(self, key: int) -> str:
        """Pipeline name a now-deleted queue key belonged to ("" when
        unknown) — keeps post-delete drop records attributable."""
        with self._lock:
            return self._retired_names.get(key, "")

    # -- producer -----------------------------------------------------------

    def push_queue(self, key: int, group: PipelineEventGroup) -> bool:
        with self._lock:
            q = self._queues.get(key)
        if q is None:
            return False
        # loongslo: the ingest stamp is minted at this same single admit
        # hook, BEFORE the push — a consumer popping the group immediately
        # must never race a post-push metadata write.  A refused push is
        # cancelled below (the caller rolls the group back: not admitted)
        if slo.is_on():
            slo.stamp_ingest(q.pipeline_name, group)
        pushed = q.push(group)
        if pushed:
            # loongledger ingest boundary: every input funnels through this
            # admit (file server, long-tail inputs, bench/test harnesses),
            # so ONE hook covers them all; a rejected push is rolled back
            # by the caller and never counted — the agent owns an event
            # only once it is admitted
            if ledger.is_on():
                ledger.record(q.pipeline_name, ledger.B_INGEST,
                              len(group), group.data_size())
            with self._data_cv:
                self._data_cv.notify()
        elif slo.is_on():
            slo.cancel_group(group)
        return pushed

    def is_valid_to_push(self, key: int) -> bool:
        q = self.get_queue(key)
        return q is not None and q.is_valid_to_push()

    # -- consumer -----------------------------------------------------------

    def pop_item(self, timeout: float = 0.2
                 ) -> Optional[Tuple[int, PipelineEventGroup]]:
        """Priority-ordered, round-robin within each priority level
        (reference ProcessQueueManager.h:91)."""
        item = self._try_pop()
        if item is not None:
            return item
        with self._data_cv:
            self._data_cv.wait(timeout)
        return self._try_pop()

    def pop_run(self, timeout: float = 0.2,
                max_groups: int = RUN_MAX_GROUPS,
                max_bytes: int = RUN_MAX_BYTES
                ) -> Optional[Tuple[int, List[PipelineEventGroup]]]:
        """Backlog-aware pop (loongcolumn): like pop_item, but drains a RUN
        of consecutive groups from the selected queue — sized by what is
        actually queued (occupancy/bytes caps), one group when traffic
        trickles.  All groups of a run share one queue key (one pipeline),
        so the consumer processes them through one chain invocation."""
        run = self._try_pop_run(max_groups, max_bytes)
        if run is not None:
            return run
        with self._data_cv:
            self._data_cv.wait(timeout)
        return self._try_pop_run(max_groups, max_bytes)

    def _prio_snapshot(self):
        with self._lock:
            if self._snapshot_version != self._version:
                self._by_prio = {p: [] for p in range(PRIORITY_COUNT)}
                for q in self._queues.values():
                    # KeyError here = misconfigured priority; silently
                    # parking the queue in an unvisited bucket would stall
                    # its data instead
                    self._by_prio[q.priority].append(q)
                self._snapshot_version = self._version
            return self._by_prio, dict(self._rr_cursor)

    def _try_pop(self) -> Optional[Tuple[int, PipelineEventGroup]]:
        by_prio, cursors = self._prio_snapshot()
        for prio in range(PRIORITY_COUNT):
            level = by_prio.get(prio)
            if not level:
                continue
            start = cursors.get(prio, 0) % len(level)
            for i in range(len(level)):
                q = level[(start + i) % len(level)]
                group = q.pop()
                if group is not None:
                    with self._lock:
                        self._rr_cursor[prio] = (start + i + 1) % len(level)
                    return q.key, group
        return None

    def _try_pop_run(self, max_groups: int, max_bytes: int
                     ) -> Optional[Tuple[int, List[PipelineEventGroup]]]:
        by_prio, cursors = self._prio_snapshot()
        for prio in range(PRIORITY_COUNT):
            level = by_prio.get(prio)
            if not level:
                continue
            start = cursors.get(prio, 0) % len(level)
            for i in range(len(level)):
                q = level[(start + i) % len(level)]
                groups = q.pop_run(max_groups, max_bytes)
                if groups:
                    with self._lock:
                        self._rr_cursor[prio] = (start + i + 1) % len(level)
                    return q.key, groups
        return None

    def all_empty(self) -> bool:
        with self._lock:
            queues = list(self._queues.values())
        return all(q.empty() for q in queues)

    def wake_up(self) -> None:
        with self._data_cv:
            self._data_cv.notify_all()

    def queue_names(self):
        with self._lock:
            return {k: q.pipeline_name for k, q in self._queues.items()}
