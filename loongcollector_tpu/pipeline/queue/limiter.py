"""Send-path limiters.

Reference: core/collection_pipeline/limiter/ — RateLimiter::FlowControl
(token-style byte budget per second) and ConcurrencyLimiter
(ConcurrencyLimiter.h:37,59-67,115-116): AIMD per-destination concurrency
with fast/slow fallback ratios.  Host-side logic, unchanged by the TPU
redesign (network egress is not device work — SURVEY.md §2.7).
"""

from __future__ import annotations

import threading
import time


class RateLimiter:
    """Byte-budget token bucket: at most `max_bytes_per_sec` over 1s windows."""

    def __init__(self, max_bytes_per_sec: int):
        self.max_bytes_per_sec = max_bytes_per_sec
        self._window_start = 0.0
        self._window_bytes = 0
        self._lock = threading.Lock()

    def is_valid_to_pop(self) -> bool:
        if self.max_bytes_per_sec <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            if now - self._window_start >= 1.0:
                return True
            return self._window_bytes < self.max_bytes_per_sec

    def post_pop(self, size: int) -> None:
        if self.max_bytes_per_sec <= 0:
            return
        with self._lock:
            now = time.monotonic()
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._window_bytes = 0
            self._window_bytes += size


class ConcurrencyLimiter:
    """AIMD in-flight budget per destination (region/host/logstore).

    OnSuccess: +1 up to the cap after `INCREASE_AFTER` consecutive successes.
    OnFail: multiplicative decrease — fast (×0.5) for hard errors, slow
    (×0.8) for soft throttling, mirroring the reference's fast/slow fallback
    ratios (ConcurrencyLimiter.h:115-116).
    """

    FAST_FALL_BACK_RATIO = 0.5
    SLOW_FALL_BACK_RATIO = 0.8
    INCREASE_AFTER = 1

    def __init__(self, name: str, max_concurrency: int = 80,
                 min_concurrency: int = 1):
        self.name = name
        self.max_concurrency = max_concurrency
        self.min_concurrency = min_concurrency
        self._limit = max_concurrency
        self._in_flight = 0
        self._success_streak = 0
        self._lock = threading.Lock()

    def is_valid_to_pop(self) -> bool:
        with self._lock:
            return self._in_flight < self._limit

    def post_pop(self) -> None:
        with self._lock:
            self._in_flight += 1

    def on_done(self) -> None:
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)

    def on_success(self) -> None:
        with self._lock:
            self._success_streak += 1
            if self._success_streak >= self.INCREASE_AFTER and self._limit < self.max_concurrency:
                self._limit += 1
                self._success_streak = 0

    def on_fail(self, slow: bool = False) -> None:
        ratio = self.SLOW_FALL_BACK_RATIO if slow else self.FAST_FALL_BACK_RATIO
        with self._lock:
            self._success_streak = 0
            self._limit = max(self.min_concurrency, int(self._limit * ratio))

    @property
    def current_limit(self) -> int:
        with self._lock:
            return self._limit

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight
