from .bounded_queue import BoundedProcessQueue, CircularProcessQueue, QueueStatus
from .limiter import ConcurrencyLimiter, RateLimiter
from .process_queue_manager import ProcessQueueManager
from .sender_queue import SenderQueue, SenderQueueItem, SenderQueueManager
