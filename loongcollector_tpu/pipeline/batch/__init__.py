from .batcher import Batcher
from .flush_strategy import FlushStrategy
from .timeout_flush_manager import TimeoutFlushManager
