"""Centralised batch timeout flushing.

Reference: core/collection_pipeline/batch/TimeoutFlushManager.h:45-56 —
FlushTimeoutBatch is driven periodically by processor thread 0
(runner/ProcessorRunner.cpp:109-112) rather than per-batcher timers.
"""

from __future__ import annotations

import threading
from typing import Optional, Set


class TimeoutFlushManager:
    _instance: Optional["TimeoutFlushManager"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._batchers: Set = set()
        self._reg_lock = threading.Lock()

    @classmethod
    def instance(cls) -> "TimeoutFlushManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, batcher) -> None:
        with self._reg_lock:
            self._batchers.add(batcher)

    def unregister(self, batcher) -> None:
        with self._reg_lock:
            self._batchers.discard(batcher)

    def flush_timeout_batches(self) -> None:
        with self._reg_lock:
            batchers = list(self._batchers)
        for b in batchers:
            b.flush_timeout()
