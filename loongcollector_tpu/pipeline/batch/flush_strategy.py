"""Batch flush strategies.

Reference: core/collection_pipeline/batch/FlushStrategy.h — MinCnt /
MinSizeBytes / MaxSizeBytes / TimeoutSecs.  A batch flushes when it reaches
the min count/size, must flush before exceeding the max size, and is flushed
by timer after the timeout.
"""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass
class FlushStrategy:
    min_cnt: int = 4096
    min_size_bytes: int = 512 * 1024
    max_size_bytes: int = 5 * 1024 * 1024
    timeout_secs: float = 1.0

    def need_flush_by_count(self, cnt: int) -> bool:
        return self.min_cnt > 0 and cnt >= self.min_cnt

    def need_flush_by_size(self, size: int) -> bool:
        return self.min_size_bytes > 0 and size >= self.min_size_bytes

    def size_would_exceed(self, size: int, add: int) -> bool:
        return self.max_size_bytes > 0 and size + add > self.max_size_bytes

    def need_flush_by_time(self, create_time: float) -> bool:
        return self.timeout_secs > 0 and (time.monotonic() - create_time) >= self.timeout_secs
