"""Per-flusher event batching.

Reference: core/collection_pipeline/batch/Batcher.h:42-44 — a templated
two-stage (event batch → group batch) accumulator keyed by the group's
(source, topic, tags) so merged batches stay homogeneous; flush strategy from
FlushStrategy.h; timeout flushing driven centrally (TimeoutFlushManager,
pumped by processor thread 0 — runner/ProcessorRunner.cpp:109-112).

TPU-first note: batches keep groups whole (columnar groups are already
batched tensors); merging concatenates group lists, not per-event copies.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ...models import PipelineEventGroup
from .flush_strategy import FlushStrategy
from .timeout_flush_manager import TimeoutFlushManager


class _BatchState:
    __slots__ = ("groups", "event_cnt", "size_bytes", "create_time")

    def __init__(self) -> None:
        self.groups: List[PipelineEventGroup] = []
        self.event_cnt = 0
        self.size_bytes = 0
        self.create_time = time.monotonic()


def _group_key(group: PipelineEventGroup) -> Tuple:
    tags = tuple(sorted((k, v.to_bytes()) for k, v in group.tags.items()))
    return tags


class Batcher:
    """Accumulates groups per key; emits batches (lists of groups) to the
    flusher's SerializeAndPush callback."""

    def __init__(self, strategy: Optional[FlushStrategy] = None,
                 on_flush: Optional[Callable[[List[PipelineEventGroup]], None]] = None,
                 flusher_id: str = "", pipeline_name: str = ""):
        self.strategy = strategy or FlushStrategy()
        self.on_flush = on_flush
        self._batches: Dict[Tuple, _BatchState] = {}
        # events claimed out of _batches but still inside on_flush (the
        # write can block): occupancy for pending_events() — without it a
        # flush stalled mid-write leaves the events in no counter and the
        # conservation auditor could read a stable ledger + zero occupancy
        # as a quiesce with a nonzero residual (loongledger)
        self._emitting_events = 0
        self._lock = threading.Lock()
        self.flusher_id = flusher_id
        self.pipeline_name = pipeline_name
        TimeoutFlushManager.instance().register(self)

    def add(self, group: PipelineEventGroup) -> None:
        size = group.data_size()
        cnt = len(group)
        to_flush: List[Tuple[List[PipelineEventGroup], int]] = []
        with self._lock:
            key = _group_key(group)
            st = self._batches.get(key)
            if st is None:
                st = _BatchState()
                self._batches[key] = st
            if st.groups and self.strategy.size_would_exceed(st.size_bytes, size):
                to_flush.append((st.groups, st.event_cnt))
                self._emitting_events += st.event_cnt
                self._batches[key] = st = _BatchState()
            st.groups.append(group)
            st.event_cnt += cnt
            st.size_bytes += size
            if (self.strategy.need_flush_by_count(st.event_cnt)
                    or self.strategy.need_flush_by_size(st.size_bytes)
                    # backlog-aware hand-off (loongcolumn): while traffic
                    # flows, a batch past its timeout flushes on the very
                    # add that finds it due — the 1 s central pump is only
                    # the idle-pipeline deadline fallback, so batch latency
                    # tracks the configured timeout, not the pump cadence
                    or self.strategy.need_flush_by_time(st.create_time)):
                to_flush.append((st.groups, st.event_cnt))
                self._emitting_events += st.event_cnt
                del self._batches[key]
        self._emit_batches(to_flush)

    def pending_events(self) -> int:
        """Events currently held by open batches or mid-flush — the
        ledger's live-occupancy probe (loongledger): an event parked here
        is inflight, not lost."""
        with self._lock:
            return (sum(st.event_cnt for st in self._batches.values())
                    + self._emitting_events)

    def flush_timeout(self) -> None:
        to_flush = []
        with self._lock:
            for key in list(self._batches):
                st = self._batches[key]
                if st.groups and self.strategy.need_flush_by_time(st.create_time):
                    to_flush.append((st.groups, st.event_cnt))
                    self._emitting_events += st.event_cnt
                    del self._batches[key]
        self._emit_batches(to_flush)

    def flush_all(self) -> None:
        with self._lock:
            pending = [(st.groups, st.event_cnt)
                       for st in self._batches.values() if st.groups]
            self._emitting_events += sum(n for _, n in pending)
            self._batches.clear()
        self._emit_batches(pending)

    def _emit_batches(self,
                      batches: List[Tuple[List[PipelineEventGroup], int]]
                      ) -> None:
        for idx, (groups, n) in enumerate(batches):
            try:
                self._emit(groups, n)
            except BaseException:
                # the unemitted tail is genuinely lost with this raise —
                # release its occupancy claim so the system can still
                # quiesce; the loss then surfaces as a ledger residual
                # (the auditor firing on it is by design)
                with self._lock:
                    self._emitting_events -= sum(
                        m for _, m in batches[idx + 1:])
                raise

    def _emit(self, groups: List[PipelineEventGroup], n_events: int) -> None:
        try:
            if self.on_flush is not None and groups:
                self.on_flush(groups)
        finally:
            with self._lock:
                self._emitting_events -= n_events

    def close(self) -> None:
        TimeoutFlushManager.instance().unregister(self)
