"""CollectionPipeline: config → plugin chain → runtime wiring.

Reference: core/collection_pipeline/CollectionPipeline.cpp —
Init (:77): build inputs/processors/flushers from the registry (:109-204),
wire inner processors supplied by inputs (:236-256), create the process
queue + feedback + sender queues (:306-358), build the router (:453-480).
Start (:393) brings plugins up sink-to-source so no data drops;
Stop (:491) is source-to-sink with a drain wait (:659-677).
Process (:419) runs inner then user processors; Send routes to flushers.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List, Optional

from ..models import PipelineEventGroup
from ..monitor import ledger, slo
from ..monitor.metrics import MetricsRecord
from ..runner import ack_watermark
from ..utils.logger import get_logger
from .plugin.instance import FlusherInstance, InputInstance, ProcessorInstance
from .plugin.interface import PluginContext
from .plugin.registry import PluginRegistry
from .route.router import Router

log = get_logger("pipeline")

_queue_keys = itertools.count(1)


def next_queue_key() -> int:
    return next(_queue_keys)


class _AggTimeoutHook:
    """Adapter letting the aggregator ride TimeoutFlushManager's cadence
    (processor thread 0 drives it, ProcessorRunner.cpp:109-112)."""

    def __init__(self, pipeline: "CollectionPipeline"):
        self._pipeline = pipeline

    def flush_timeout(self) -> None:
        agg = self._pipeline.aggregator
        if agg is None:
            return
        hook = getattr(agg, "flush_timeout", None)
        if hook is not None:
            self._pipeline._send_direct(hook())


class _ProcDrainHook:
    """Periodic drain for processors holding cross-group state (e.g.
    split_multiline's carried open records): groups they release run
    through the REST of the processor chain and the normal send path."""

    def __init__(self, pipeline: "CollectionPipeline", chain_idx: int,
                 inst: ProcessorInstance):
        self._pipeline = pipeline
        self._chain_idx = chain_idx
        self._inst = inst

    def flush_timeout(self) -> None:
        fn = getattr(self._inst.plugin, "flush_timeout_groups", None)
        if fn is not None:
            self._pipeline.drain_from(self._chain_idx, fn())


class CollectionPipeline:
    def __init__(self) -> None:
        self.name = ""
        # loongtenant: reload generation stamp — the manager bumps it per
        # applied config so /debug/status and the flight recorder can name
        # WHICH incarnation of a pipeline an event belongs to.  0 = never
        # managed (tests constructing pipelines directly)
        self.generation = 0
        self.config: Dict[str, Any] = {}
        self.context = PluginContext()
        self.inputs: List[InputInstance] = []
        self.inner_processors: List[ProcessorInstance] = []
        self.processors: List[ProcessorInstance] = []
        self.flushers: List[FlusherInstance] = []
        self.router = Router()
        self.aggregator = None
        self._agg_timeout_hook = _AggTimeoutHook(self)
        self.process_queue_key = 0
        self._fused_runs = []
        self._fused_by_head = {}
        self._in_process_cnt = 0
        self._in_process_zero = threading.Condition()
        self.metrics = None
        self._metric_records = []

    # ------------------------------------------------------------------

    def init(self, name: str, config: Dict[str, Any],
             process_queue_manager=None, sender_queue_manager=None,
             reuse_queue_key: Optional[int] = None) -> bool:
        self.name = name
        self.config = config
        self.context = PluginContext(pipeline_name=name, config=config)
        self.context.pipeline = self
        self.metrics = MetricsRecord(category="pipeline",
                                     labels={"pipeline_name": name})
        self._metric_records.append(self.metrics)
        registry = PluginRegistry.instance()
        registry.load_static_plugins()

        global_cfg = config.get("global", {})
        self.context.global_config = global_cfg

        # extensions FIRST: other plugins resolve them by name at init
        # (reference pkg/pipeline/extensions + plugins/extension/)
        for ecfg in config.get("extensions", []):
            etyp = ecfg.get("Type", "")
            ext = registry.create_extension(etyp)
            if ext is None or not ext.init(ecfg, self.context):
                return self._abort_init()
            key = etyp
            if ecfg.get("Alias"):
                key = f"{etyp}/{ecfg['Alias']}"
            if key in self.context.extensions:
                # silent overwrite would leave the shadowed instance
                # unstoppable and auth with the wrong credentials
                log.error("duplicate extension %r (use Alias)", key)
                return self._abort_init()
            self.context.extensions[key] = ext

        # inputs
        for i, icfg in enumerate(config.get("inputs", [])):
            typ = icfg.get("Type", "")
            plugin = registry.create_input(typ)
            if plugin is None:
                return self._abort_init()
            inst = InputInstance(plugin, plugin_id=f"{typ}/{i}")
            self._metric_records.append(inst.metrics)
            if not inst.init(icfg, self.context):
                return self._abort_init()
            self.inputs.append(inst)
            # inputs may supply inner processors (reference :236-256, e.g.
            # InputFile creates the split/multiline processors)
            for pcfg in getattr(plugin, "inner_processor_configs", lambda: [])():
                ptyp = pcfg.get("Type", "")
                pplugin = registry.create_processor(ptyp)
                if pplugin is None:
                    return self._abort_init()
                pinst = ProcessorInstance(pplugin, plugin_id=f"{ptyp}/inner")
                self._metric_records.append(pinst.metrics)
                if not pinst.init(pcfg, self.context):
                    return self._abort_init()
                self.inner_processors.append(pinst)

        # user processors
        for i, pcfg in enumerate(config.get("processors", [])):
            typ = pcfg.get("Type", "")
            plugin = registry.create_processor(typ)
            if plugin is None:
                return self._abort_init()
            inst = ProcessorInstance(plugin, plugin_id=f"{typ}/{i}")
            self._metric_records.append(inst.metrics)
            if not inst.init(pcfg, self.context):
                return self._abort_init()
            self.processors.append(inst)

        # aggregator stage (reference pkg/pipeline/aggregator.go:24-51 —
        # at most one per pipeline, between processors and flushers)
        agg_cfgs = config.get("aggregators", [])
        if agg_cfgs:
            acfg = agg_cfgs[0]
            atyp = acfg.get("Type", "")
            self.aggregator = registry.create_aggregator(atyp)
            if self.aggregator is None or \
                    not self.aggregator.init(acfg, self.context):
                return self._abort_init()
            from ..pipeline.batch.timeout_flush_manager import \
                TimeoutFlushManager
            TimeoutFlushManager.instance().register(self._agg_timeout_hook)

        # flushers + router
        route_configs = []
        for i, fcfg in enumerate(config.get("flushers", [])):
            typ = fcfg.get("Type", "")
            plugin = registry.create_flusher(typ)
            if plugin is None:
                return self._abort_init()
            inst = FlusherInstance(plugin, plugin_id=f"{typ}/{i}")
            plugin.plugin_id = inst.plugin_id
            self._metric_records.append(inst.metrics)
            plugin.queue_key = next_queue_key()
            self._sender_queue_manager = sender_queue_manager
            if sender_queue_manager is not None:
                plugin.sender_queue = sender_queue_manager.create_or_reuse_queue(
                    plugin.queue_key, pipeline_name=name)
            if not inst.init(fcfg, self.context):
                self.flushers.append(inst)  # ensure _abort_init stops it
                return self._abort_init()
            self.flushers.append(inst)
            route_configs.append((i, fcfg.get("Match")))
        self.router.init(route_configs)

        # processors holding cross-group state get a timeout-drain hook so
        # their held records flush on idle pipelines too
        from ..pipeline.batch.timeout_flush_manager import TimeoutFlushManager
        chain = self.inner_processors + self.processors
        self._drain_hooks = []
        for idx, inst in enumerate(chain):
            if hasattr(inst.plugin, "flush_timeout_groups"):
                hook = _ProcDrainHook(self, idx, inst)
                self._drain_hooks.append(hook)
                TimeoutFlushManager.instance().register(hook)

        # loongresident: plan fused device-stage runs over the final chain
        # (pure description — programs compile on first dispatch / from
        # the content-addressed cache).  LOONG_FUSED gates execution, not
        # planning, so flipping it needs no pipeline reload.
        from .fused_chain import plan_fusion
        self._fused_runs = plan_fusion(self.inner_processors
                                       + self.processors)
        self._fused_by_head = {r.head: r for r in self._fused_runs}

        # process queue: a modified pipeline keeps its key so queued groups
        # survive the swap (reference ExactlyOnceQueueManager/QueueKeyManager
        # keep keys stable per config name)
        self.process_queue_key = (reuse_queue_key if reuse_queue_key
                                  else next_queue_key())
        self.context.process_queue_key = self.process_queue_key
        self.context.process_queue_manager = process_queue_manager
        if process_queue_manager is not None:
            from ..pipeline.queue.bounded_queue import DEFAULT_MAX_BYTES
            priority = int(global_cfg.get("Priority", 1))
            capacity = int(global_cfg.get("ProcessQueueCapacity", 20))
            circular = bool(global_cfg.get("CircularProcessQueue", False))
            # loongcolumn: byte watermark next to the group-count bound —
            # 0 disables (docs/performance.md "Backlog-aware hand-off")
            max_bytes = int(global_cfg.get("ProcessQueueMaxBytes",
                                           DEFAULT_MAX_BYTES))
            q = process_queue_manager.create_or_reuse_queue(
                self.process_queue_key, priority, capacity, name,
                circular=circular, max_bytes=max_bytes)
        return True

    def _abort_init(self) -> bool:
        """Failed init: release everything already constructed (batchers
        registered with TimeoutFlushManager, sender queues, metric records)."""
        self.release()
        return False

    def release(self) -> None:
        """Free pipeline-owned global registrations.  Called on failed init
        and after stop() by the manager."""
        from ..pipeline.batch.timeout_flush_manager import TimeoutFlushManager
        if self.aggregator is not None:
            TimeoutFlushManager.instance().unregister(self._agg_timeout_hook)
        for hook in getattr(self, "_drain_hooks", []):
            TimeoutFlushManager.instance().unregister(hook)
        for f in self.flushers:
            try:
                f.plugin.stop(True)
            except Exception:  # noqa: BLE001
                pass
        sqm = getattr(self, "_sender_queue_manager", None)
        if sqm is not None:
            for f in self.flushers:
                sqm.mark_for_deletion(f.plugin.queue_key)
        for rec in self._metric_records:
            rec.mark_deleted()

    # ------------------------------------------------------------------

    def start(self) -> None:
        """Sink-to-source order (reference :393-417)."""
        self.start_flushers()
        self.start_inputs()

    def start_flushers(self) -> None:
        """Bring the sink side up.  During a hot reload the manager calls
        this BEFORE the old generation stops: the moment the new
        generation is registered under the name, groups popped from the
        (shared) process queue route through a chain whose flushers are
        already ready — generation N+1 admits before N stops."""
        for f in self.flushers:
            f.start()

    def start_inputs(self) -> None:
        """Bring the source side up.  Deliberately separate from
        start_flushers: during a reload the OLD generation's inputs must
        stop before the new generation's start (two live tails of one
        file would double-read), so the manager sequences
        start_flushers → drain old → start_inputs."""
        for i in self.inputs:
            i.start()

    def stop(self, is_removing: bool = False) -> None:
        """Source-to-sink with drain (reference :491-532,659-677)."""
        for i in self.inputs:
            i.stop(is_removing)
        self.wait_all_items_in_process_finished()
        # release processor-held state (carried multiline records) through
        # the rest of the chain before the final batch flush
        chain = self.inner_processors + self.processors
        for idx, inst in enumerate(chain):
            drain = getattr(inst.plugin, "drain_groups", None)
            if drain is not None:
                self.drain_from(idx, drain())
        self.flush_batch()
        for f in self.flushers:
            f.stop(is_removing)
        for ext in self.context.extensions.values():
            try:
                ext.stop()
            except Exception:  # noqa: BLE001
                pass

    def drain_from(self, chain_idx: int,
                   groups: List[PipelineEventGroup]) -> None:
        """Run released groups through processors AFTER chain_idx, then the
        normal send path (aggregator + router + flushers)."""
        if not groups:
            return
        if ledger.is_on():
            # held events re-enter the chain: the matching credit for the
            # process_drop their holding stage ledgered when it kept them
            ledger.record(self.name, ledger.B_PROCESS_EXPAND,
                          sum(len(g) for g in groups), tag="drain")
        chain = self.inner_processors + self.processors
        for g in groups:
            for inst in chain[chain_idx + 1:]:
                inst.process([g])
        self.send(groups)

    # ------------------------------------------------------------------

    def process(self, groups: List[PipelineEventGroup]) -> None:
        finish = self.process_begin(groups)
        if finish is not None:
            finish()

    def process_begin(self, groups: List[PipelineEventGroup]):
        """Run the processor chain up to and including the first
        device-dispatch-capable processor's dispatch (async device plane,
        SURVEY §7 step 4).

        Returns None when the chain ran to completion synchronously;
        otherwise a zero-arg continuation that materialises the device work
        and runs the remaining processors — call it exactly once.  While the
        continuation is outstanding the group counts as in-process for the
        stop/drain barrier (wait_all_items_in_process_finished)."""
        with self._in_process_zero:
            self._in_process_cnt += 1
        if ledger.is_on():
            ledger.record(self.name, ledger.B_PROCESS_IN,
                          sum(len(g) for g in groups),
                          sum(g.data_size() for g in groups))
        try:
            cont = self._walk_chain(groups, 0, allow_async=True)
        except BaseException:
            self._exit_process()
            raise
        if cont is None:
            self._exit_process()
            return None

        def finish():
            try:
                cont()
            finally:
                self._exit_process()
        return finish

    def _walk_chain(self, groups: List[PipelineEventGroup], i: int,
                    allow_async: bool):
        """Index-walk the processor chain from ``i``.  A fused run
        (loongresident) executes as ONE async stage; with ``allow_async``
        the first stage that leaves device work in flight returns a
        continuation (the runner's overlap window), which finishes that
        stage and walks the REST of the chain inline — exactly the old
        single-async-stage contract, now fusion-aware on both legs."""
        chain = self.inner_processors + self.processors
        while i < len(chain):
            run = self._fused_by_head.get(i)
            if run is not None and run.enabled():
                tokens = run.dispatch(groups)
                nxt = run.end
                if any(t is not None for t in tokens):
                    if allow_async:
                        def finish_run(run=run, tokens=tokens, nxt=nxt):
                            run.complete(groups, tokens)
                            self._walk_chain(groups, nxt,
                                             allow_async=False)
                        return finish_run
                    run.complete(groups, tokens)
                i = nxt
                continue
            inst = chain[i]
            if not getattr(inst.plugin, "supports_async_dispatch", False):
                inst.process(groups)
                i += 1
                continue
            tokens = inst.process_dispatch(groups)
            if all(t is None for t in tokens):
                # nothing stayed in flight (host-tier route / empty
                # groups): finish the chain inline — deferring would
                # only delay the send.  complete() still runs so the
                # instance's out_events/cost metrics stay truthful.
                inst.process_complete(groups, tokens)
                i += 1
                continue
            if allow_async:
                rest_idx = i + 1

                def finish(inst=inst, tokens=tokens, rest_idx=rest_idx):
                    inst.process_complete(groups, tokens)
                    self._walk_chain(groups, rest_idx, allow_async=False)
                return finish
            inst.process_complete(groups, tokens)
            i += 1
        return None

    def _exit_process(self) -> None:
        with self._in_process_zero:
            self._in_process_cnt -= 1
            if self._in_process_cnt == 0:
                self._in_process_zero.notify_all()

    def send(self, groups: List[PipelineEventGroup]) -> bool:
        led = ledger.is_on()
        if led:
            ledger.record(self.name, ledger.B_PROCESS_OUT,
                          sum(len(g) for g in groups))
        if self.aggregator is not None:
            n_in = sum(len(g) for g in groups)
            staged: List[PipelineEventGroup] = []
            for g in groups:
                staged.extend(self.aggregator.add(g))
            # groups the aggregator absorbed (folded into rollup state, not
            # passed through) lose span identity here: force-ack so their
            # SOURCE bytes never pin the checkpoint watermark
            staged_ids = {id(s) for s in staged}
            consumed = [g for g in groups if id(g) not in staged_ids]
            if consumed:
                ack_watermark.ack_groups(consumed, force=True)
                if slo.is_on():
                    # absorbed into rollup state: the stamp retires WITHOUT
                    # a sojourn sample — the rollup minted at window close
                    # gets its own stamp (_send_direct) and carries the
                    # delivery latency from there
                    slo.retire_groups(consumed)
            groups = staged
            if led and not getattr(self.aggregator,
                                   "ledger_self_accounting", False):
                # a stateful aggregator holds (delta < 0, a process_drop it
                # repays via _send_direct at flush) or mints rollup events
                # (delta > 0, process_expand) — either way the chain stays
                # balanced without instrumenting every aggregator plugin.
                # Self-accounting aggregators (loongagg's fold) book their
                # own agg_in/agg_fold/agg_emit boundaries instead.
                delta = sum(len(g) for g in groups) - n_in
                if delta < 0:
                    ledger.record(self.name, ledger.B_PROCESS_DROP, -delta,
                                  tag="aggregator")
                elif delta > 0:
                    ledger.record(self.name, ledger.B_PROCESS_EXPAND, delta,
                                  tag="aggregator")
        ok = True
        for group in groups:
            if group.empty():
                # filtered to nothing: terminal for its SOURCE span
                ack_watermark.ack_groups([group], force=True)
                if slo.is_on():
                    slo.retire_groups([group])
                continue
            ok = self._route_group(group, led) and ok
        return ok

    def _route_group(self, group: PipelineEventGroup, led: bool) -> bool:
        idxs = self.router.route(group)
        if not idxs:
            # no flusher matched: the group is terminally discarded
            ack_watermark.ack_groups([group], force=True)
            if led:
                ledger.record(self.name, ledger.B_DROP, len(group),
                              group.data_size(), tag="no_route")
            if slo.is_on():
                slo.observe_groups(self.name, [group], slo.OUTCOME_DROP)
        elif len(idxs) > 1:
            # every extra matching flusher mints a copy of the group's
            # events — raise the span's terminal refcount BEFORE any copy
            # can ack, or a fast first sink advances the watermark while
            # the second copy is still in flight
            ack_watermark.note_fanout(group, len(idxs))
            if led:
                ledger.record(self.name, ledger.B_FANOUT,
                              (len(idxs) - 1) * len(group))
            if slo.is_on():
                # the ingest stamp's refcount mirrors the span fanout: each
                # copy's terminal observes its own sojourn
                slo.note_fanout(group, len(idxs))
        ok = True
        for idx in idxs:
            ok = self.flushers[idx].send(group) and ok
        return ok

    def _send_direct(self, groups: List[PipelineEventGroup]) -> None:
        led = ledger.is_on()
        self_acct = getattr(self.aggregator, "ledger_self_accounting", False)
        for group in groups:
            if group.empty():
                ack_watermark.ack_groups([group], force=True)
                if slo.is_on():
                    slo.retire_groups([group])
                continue
            if slo.is_on():
                # aggregator rollups are minted stampless (the checker's
                # explicit exemption): window close IS their ingest instant
                slo.ensure_stamp(self.name, group)
            if led:
                if not self_acct:
                    # aggregator-held events released by timeout/final
                    # flush: the credit matching the "aggregator"-tagged
                    # process_drop (self-accounting aggregators booked
                    # agg_emit at emission instead)
                    ledger.record(self.name, ledger.B_PROCESS_EXPAND,
                                  len(group), tag="aggregator_flush")
                ledger.record(self.name, ledger.B_PROCESS_OUT, len(group))
            self._route_group(group, led)

    def flush_batch(self) -> None:
        if self.aggregator is not None:
            self._send_direct(self.aggregator.flush())
        for f in self.flushers:
            f.plugin.flush_all()

    def wait_all_items_in_process_finished(self, timeout: float = 10.0) -> bool:
        with self._in_process_zero:
            if self._in_process_cnt == 0:
                return True
            return self._in_process_zero.wait_for(
                lambda: self._in_process_cnt == 0, timeout)

    def has_go_pipeline(self) -> bool:
        return False
