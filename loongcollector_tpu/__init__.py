"""loongcollector_tpu — a TPU-native observability data collector.

A brand-new framework with the capabilities of alibaba/loongcollector
(reference: /root/reference): it discovers and tails logs, collects metrics,
traces and events, parses and transforms them in-process, and ships them to
pluggable sinks with batching, back-pressure, checkpointing and exactly-once
support.  Unlike the reference (per-event boost::regex on CPU threads,
core/plugin/processor/ProcessorParseRegexNative.cpp), the parsing data plane
here runs as batched kernels on TPU via JAX/XLA: event groups are accumulated
into fixed-width device batches, the zero-copy SourceBuffer arena is
transferred to HBM, and per-event field (offset,len) spans are returned into
the same string-view event model.

Package layout:
  models/    — arena-backed zero-copy event model (reference: core/models/)
  ops/       — TPU compute: regex/grok/delimiter/JSON kernels + compilers
  pipeline/  — queues, plugin registry, batcher, router, serializers
               (reference: core/collection_pipeline/)
  processor/ — processor plugins, TPU + CPU implementations
               (reference: core/plugin/processor/)
  flusher/   — sink plugins (reference: core/plugin/flusher/)
  input/     — input plugins, file tailing (reference: core/file_server/)
  runner/    — thread engines (reference: core/runner/)
  config/    — config loading/watching (reference: core/config/)
  monitor/   — self metrics and alarms (reference: core/monitor/)
  parallel/  — device mesh / sharding of the parse data plane across chips
  utils/     — flags, logging, string views (reference: core/common/)
"""

__version__ = "0.1.0"
