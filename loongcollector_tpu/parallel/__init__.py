from .mesh import ShardedParsePlane, make_mesh
