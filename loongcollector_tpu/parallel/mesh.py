"""Multi-chip scale-out of the parse data plane (loongmesh).

Reference reality (SURVEY.md §2.7, §5.8): LoongCollector agents are
independent processes — no NCCL/MPI; its parallelism is pipelined threads +
per-pipeline queues.  The TPU-native equivalent for one host owning multiple
chips: **data-parallel sharding of event batches over an ICI-connected
device mesh**.  Events are embarrassingly parallel, so the batch dimension
shards cleanly; the only cross-chip communication is tiny psum'd telemetry
(match counts / byte counts for the self-monitor), which rides ICI.

Design: `shard_map` over a 1-D ('dp',) mesh; each chip runs the same
gather-free extraction kernel on its batch shard; jax.lax.psum aggregates
stats.  Multi-host (DCN) follows the same SPMD program — jax.distributed
initialises the global mesh and the batch dimension spans hosts; no code
change in the kernel.

loongmesh (ISSUE 9) promoted :class:`ShardedKernel` from a bench adapter
into the production dispatch path:

* batches arrive **shard-aligned**: the engine packs into batch-ring slots
  whose B is already a mesh multiple (``ShardedKernel.batch_multiple``
  feeds ``pad_batch(multiple_of=...)``), so the hot path never pays the
  old host-side ``np.concatenate`` copy.  Direct callers with odd B fall
  back to a kernel-private persistent pad buffer (same
  zero-the-tail-in-place discipline as a BatchRing slot, without entering
  the ring's lease ledger).
* dispatch goes through a **donated** sharded step where the backend
  supports donation: each call's inputs are transient per-shard staging
  copies, so XLA reuses their HBM for the outputs — DMA of batch N+1
  overlaps compute of N on every chip.
* the psum'd telemetry no longer dies on device: per-dispatch stats are
  queued and folded — off the hot path — into the process metrics
  (``mesh_matched_total`` / ``mesh_events_total`` / ``mesh_bytes_total``,
  labelled by chip count) plus per-chip row-occupancy accounting, all
  surfaced in ``/debug/status`` (monitor/exposition.collect_status) and
  ``bench.py`` ``extra.multichip``.

``LOONG_MESH_CHIPS`` caps the mesh width (the bench chips=1/2/4/8 sweep's
knob); per-chip *lanes* — affinity, breakers, chaos — live in
ops/chip_lanes.py.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import xprof
from ..ops.compile_watch import watched_jit
from ..ops.device_plane import mem_note_alloc, mem_note_free
from ..ops.regex.program import SegmentProgram
from ..ops.kernels.field_extract import build_extract_fn, donation_supported


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    if n_devices is None:
        from ..ops.chip_lanes import mesh_chip_cap
        n_devices = mesh_chip_cap()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class ShardedParsePlane:
    """The parse step jitted over a device mesh.

    step(rows [B,L], lengths [B]) ->
        ok [B] bool, cap_off [B,C] i32, cap_len [B,C] i32,
        stats {matched, events, bytes} — psum-replicated across the mesh.

    B must be divisible by the mesh size (the batch builder pads to a mesh
    multiple; see ShardedKernel.batch_multiple).
    """

    def __init__(self, program: SegmentProgram, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.program = program
        extract = build_extract_fn(program)
        axis = self.mesh.axis_names[0]

        def _local_step(rows, lengths):
            ok, off, length = extract(rows, lengths)
            stats = {
                "matched": jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), axis),
                "events": jax.lax.psum(
                    jnp.sum((lengths > 0).astype(jnp.int32)), axis),
                "bytes": jax.lax.psum(jnp.sum(lengths), axis),
            }
            return ok, off, length, stats

        try:
            from jax import shard_map  # jax ≥ 0.8 (check_rep retired)
            kw = {}
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map
            kw = {"check_rep": False}
        sharded = shard_map(
            _local_step, mesh=self.mesh,
            in_specs=(P(axis, None), P(axis)),
            out_specs=(P(axis), P(axis, None), P(axis, None),
                       {"matched": P(), "events": P(), "bytes": P()}),
            **kw)
        self._fn = watched_jit(sharded, "sharded_parse")
        # donated variant (loongmesh): inputs are per-dispatch staging
        # copies produced by put(), so XLA may alias their per-shard HBM
        # for the outputs.  CPU ignores donation with a per-call warning,
        # so the variant only exists where donation is real.
        self._fn_donated = (watched_jit(sharded, "sharded_parse",
                                        donate_argnums=(0, 1))
                            if donation_supported() else None)
        ax = axis
        self._in_shardings = (NamedSharding(self.mesh, P(ax, None)),
                              NamedSharding(self.mesh, P(ax)))

    def put(self, rows: np.ndarray, lengths: np.ndarray):
        """Device-put host arrays with the mesh sharding (one shard per
        chip's HBM)."""
        return (jax.device_put(rows, self._in_shardings[0]),
                jax.device_put(lengths, self._in_shardings[1]))

    def __call__(self, rows, lengths):
        return self._fn(rows, lengths)

    def donated(self, rows_d, lengths_d):
        """The donating step (falls back to the plain step off-TPU/GPU).
        Only safe for device buffers the caller will never touch again —
        put() copies qualify, a bench loop's reused device input does
        not."""
        if self._fn_donated is None:
            return self._fn(rows_d, lengths_d)
        return self._fn_donated(rows_d, lengths_d)

    @property
    def num_devices(self) -> int:
        return self.mesh.size


# ---------------------------------------------------------------------------
# mesh telemetry: psum'd stats materialised OFF the hot path


_mesh_records: Dict[int, object] = {}
_mesh_records_lock = threading.Lock()


def _mesh_record(chips: int):
    rec = _mesh_records.get(chips)
    if rec is None:
        with _mesh_records_lock:
            rec = _mesh_records.get(chips)
            if rec is None:
                from ..monitor.metrics import MetricsRecord
                rec = MetricsRecord(
                    category="device_plane",
                    labels={"component": "mesh", "chips": str(chips)})
                _mesh_records[chips] = rec
    return rec


_live_kernels: "weakref.WeakSet" = weakref.WeakSet()


def mesh_status() -> Optional[dict]:
    """Aggregate status of every live ShardedKernel (the /debug/status
    ``mesh.kernels`` section).  Folds any queued psum stats first — the
    status page is exactly the off-hot-path materialisation point the
    telemetry queue exists for.  None when the process never built one."""
    kernels = list(_live_kernels)
    if not kernels:
        return None
    out = []
    for k in kernels:
        try:
            out.append(k.status())
        except Exception:  # noqa: BLE001 — status must never 500
            pass
    return {"kernels": out} if out else None


class ShardedKernel:
    """Engine-facing adapter: makes ShardedParsePlane shaped like the
    single-device extract kernels (rows, lengths) → (ok, off, len), so the
    regex engine's async dispatch path (DevicePlane budget + watermark
    back-pressure + batch-ring slots) drives the whole mesh without
    special cases.

    The engine consults :attr:`batch_multiple` when sizing the slot, so
    production batches arrive already mesh-aligned and dispatch is
    copy-free; an unaligned direct call pads through a kernel-private
    persistent buffer (tail zeroed in place — never ``np.concatenate``).

    Telemetry: every dispatch queues its psum'd device stats; the queue is
    folded into the ``mesh_*_total`` counters off the hot path — at status
    collection (:func:`mesh_status`), via :meth:`materialize_stats`, or
    lazily when the queue outgrows the pipeline depth (the oldest entry's
    compute has long finished by then, so np.asarray is a cheap copy, not
    a device wait).  ``last_stats`` keeps the most recent dispatch's
    on-device handle for tests and ad-hoc inspection."""

    #: fold queued stats once the backlog exceeds this many dispatches —
    #: deeper than any stream depth, so the fold never blocks on compute
    STATS_QUEUE_MAX = 8

    def __init__(self, program: SegmentProgram, mesh: Optional[Mesh] = None):
        self.plane = ShardedParsePlane(program, mesh)
        self.last_stats = None
        # serializes the host-side staging of one dispatch (pad-buffer
        # reuse + per-chip accounting + device_put): multiple unbound
        # workers (LOONG_MESH_LANES=0) share this kernel through the
        # engine cache, and an unlocked numpy += loses updates while a
        # shared pad buffer could be repacked mid-transfer.  Held only
        # until the async dispatch returns — never across materialise.
        self._dispatch_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._stats_pending: deque = deque()
        self._record = _mesh_record(self.plane.num_devices)
        self._matched_total = self._record.counter("mesh_matched_total")
        self._events_total = self._record.counter("mesh_events_total")
        self._bytes_total = self._record.counter("mesh_bytes_total")
        self._dispatches_total = self._record.counter(
            "mesh_dispatches_total")
        self._pad_fallback_total = self._record.counter(
            "mesh_pad_fallback_total")
        # per-chip row occupancy, computed host-side from the lengths
        # array (one reshape + count per dispatch — no extra collective)
        m = self.plane.num_devices
        self._chip_real_rows = np.zeros(m, dtype=np.int64)
        self._chip_rows = np.zeros(m, dtype=np.int64)
        # private pad buffers for unaligned DIRECT calls, keyed (B, L):
        # reused like a one-slot ring without entering the lease ledger
        self._pad_buffers: Dict[tuple, tuple] = {}
        _live_kernels.add(self)

    @property
    def batch_multiple(self) -> int:
        """Engine contract: pack batches whose B is a multiple of this
        (pad rows zeroed in the slot) and dispatch stays copy-free."""
        return self.plane.num_devices

    # -- padding (fallback only: the engine path arrives aligned) -----------

    def _pad_to_mesh(self, rows, lengths):
        m = self.plane.num_devices
        b = rows.shape[0]
        if b % m == 0:
            return rows, lengths
        self._pad_fallback_total.add(1)
        B = b + (m - b % m)
        L = rows.shape[1]
        buf = self._pad_buffers.get((B, L))
        if buf is None:
            buf = (np.zeros((B, L), rows.dtype), np.zeros(B, lengths.dtype))
            self._pad_buffers[(B, L)] = buf
        prows, plens = buf
        prows[:b] = rows
        prows[b:] = 0
        plens[:b] = lengths
        plens[b:] = 0
        return prows, plens

    # -- telemetry -----------------------------------------------------------

    def _note_per_chip(self, lengths: np.ndarray) -> None:
        m = self.plane.num_devices
        per = np.asarray(lengths).reshape(m, -1)
        self._chip_real_rows += (per > 0).sum(axis=1)
        self._chip_rows += per.shape[1]

    def _queue_stats(self, stats) -> None:
        with self._stats_lock:
            self._stats_pending.append(stats)
            overflow = len(self._stats_pending) > self.STATS_QUEUE_MAX
        if overflow:
            self.materialize_stats(max_entries=1)

    def materialize_stats(self, max_entries: Optional[int] = None) -> dict:
        """Fold queued psum stats into the mesh_* counters (np.asarray on
        each entry — blocking only if that dispatch's compute is somehow
        still in flight, which the queue depth guards against on the lazy
        path).  Returns the counters' running totals."""
        while True:
            with self._stats_lock:
                if not self._stats_pending or max_entries == 0:
                    break
                stats = self._stats_pending.popleft()
            if max_entries is not None:
                max_entries -= 1
            try:
                self._matched_total.add(int(np.asarray(stats["matched"])))
                self._events_total.add(int(np.asarray(stats["events"])))
                self._bytes_total.add(int(np.asarray(stats["bytes"])))
            except Exception:  # noqa: BLE001 — a failed dispatch's stats
                pass           # die with it; the counters stay truthful
        return {
            "matched": self._matched_total.value,
            "events": self._events_total.value,
            "bytes": self._bytes_total.value,
        }

    def status(self) -> dict:
        totals = self.materialize_stats()
        rows = self._chip_rows
        real = self._chip_real_rows
        occ = np.divide(real, np.maximum(rows, 1)).round(4)
        return {
            "chips": self.plane.num_devices,
            "dispatches": self._dispatches_total.value,
            "pad_fallbacks": self._pad_fallback_total.value,
            "totals": totals,
            "per_chip_row_occupancy": occ.tolist(),
            "per_chip_padding_fraction":
                (1.0 - occ).round(4).tolist(),
        }

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, rows, lengths, donate: bool):
        with self._dispatch_lock:
            rows, lengths = self._pad_to_mesh(rows, lengths)
            self._note_per_chip(lengths)
            self._dispatches_total.add(1)
            # loongxprof: this runs INSIDE DevicePlane.submit's kernel
            # call when the engine dispatches the mesh, so the per-shard
            # device_put is the enclosing dispatch's real H2D leg —
            # attached via the current-dispatch TLS.  The staging copies'
            # footprint is ledgered for the duration of the dispatch call
            # (donation hands the same HBM to the outputs after that).
            xid = xprof.current_dispatch()
            staged = rows.nbytes + lengths.nbytes
            mem_note_alloc("sharded_staging", staged)
            try:
                if xid:
                    t_put = time.perf_counter()
                    rows_d, lengths_d = self.plane.put(rows, lengths)
                    xprof.leg(xid, "h2d", t_put,
                              time.perf_counter() - t_put,
                              chips=self.plane.num_devices)
                else:
                    rows_d, lengths_d = self.plane.put(rows, lengths)
                step = self.plane.donated if donate else self.plane
                ok, off, length, stats = step(rows_d, lengths_d)
            finally:
                mem_note_free("sharded_staging", staged)
        self.last_stats = stats
        self._queue_stats(stats)
        return ok, off, length

    def __call__(self, rows, lengths):
        return self._dispatch(rows, lengths, donate=False)

    def donated_call(self, rows, lengths):
        """Streaming-path dispatch (PendingParse picks this up via the
        same ``donated_call`` protocol as the single-chip kernels): the
        put() staging copies are transient, so their per-shard HBM is
        donated to the outputs."""
        return self._dispatch(rows, lengths, donate=True)
