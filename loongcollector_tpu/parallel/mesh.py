"""Multi-chip scale-out of the parse data plane.

Reference reality (SURVEY.md §2.7, §5.8): LoongCollector agents are
independent processes — no NCCL/MPI; its parallelism is pipelined threads +
per-pipeline queues.  The TPU-native equivalent for one host owning multiple
chips: **data-parallel sharding of event batches over an ICI-connected
device mesh**.  Events are embarrassingly parallel, so the batch dimension
shards cleanly; the only cross-chip communication is tiny psum'd telemetry
(match counts / byte counts for the self-monitor), which rides ICI.

Design: `shard_map` over a 1-D ('dp',) mesh; each chip runs the same
gather-free extraction kernel on its batch shard; jax.lax.psum aggregates
stats.  Multi-host (DCN) follows the same SPMD program — jax.distributed
initialises the global mesh and the batch dimension spans hosts; no code
change in the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.regex.program import SegmentProgram
from ..ops.kernels.field_extract import build_extract_fn


def make_mesh(n_devices: Optional[int] = None, axis: str = "dp") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class ShardedParsePlane:
    """The parse step jitted over a device mesh.

    step(rows [B,L], lengths [B]) ->
        ok [B] bool, cap_off [B,C] i32, cap_len [B,C] i32,
        stats {matched, events, bytes} — psum-replicated across the mesh.

    B must be divisible by the mesh size (the batch builder pads to powers
    of two, so any power-of-two mesh divides it).
    """

    def __init__(self, program: SegmentProgram, mesh: Optional[Mesh] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.program = program
        extract = build_extract_fn(program)
        axis = self.mesh.axis_names[0]

        def _local_step(rows, lengths):
            ok, off, length = extract(rows, lengths)
            stats = {
                "matched": jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), axis),
                "events": jax.lax.psum(
                    jnp.sum((lengths > 0).astype(jnp.int32)), axis),
                "bytes": jax.lax.psum(jnp.sum(lengths), axis),
            }
            return ok, off, length, stats

        try:
            from jax import shard_map  # jax ≥ 0.8 (check_rep retired)
            kw = {}
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map
            kw = {"check_rep": False}
        sharded = shard_map(
            _local_step, mesh=self.mesh,
            in_specs=(P(axis, None), P(axis)),
            out_specs=(P(axis), P(axis, None), P(axis, None),
                       {"matched": P(), "events": P(), "bytes": P()}),
            **kw)
        self._fn = jax.jit(sharded)
        ax = axis
        self._in_shardings = (NamedSharding(self.mesh, P(ax, None)),
                              NamedSharding(self.mesh, P(ax)))

    def put(self, rows: np.ndarray, lengths: np.ndarray):
        """Device-put host arrays with the mesh sharding (one shard per
        chip's HBM)."""
        return (jax.device_put(rows, self._in_shardings[0]),
                jax.device_put(lengths, self._in_shardings[1]))

    def __call__(self, rows, lengths):
        return self._fn(rows, lengths)

    @property
    def num_devices(self) -> int:
        return self.mesh.size


class ShardedKernel:
    """Engine-facing adapter: makes ShardedParsePlane shaped like the
    single-device extract kernels (rows, lengths) → (ok, off, len), so the
    regex engine's async dispatch path (DevicePlane budget + watermark
    back-pressure) drives the whole mesh without special cases.

    Batches are padded to a mesh-size multiple with zero-length rows
    (PendingParse slices the result back to n_real).  The psum'd mesh
    telemetry of the LAST dispatch stays on device in `last_stats` — the
    self-monitor can materialise it off the hot path."""

    def __init__(self, program: SegmentProgram, mesh: Optional[Mesh] = None):
        self.plane = ShardedParsePlane(program, mesh)
        self.last_stats = None

    def __call__(self, rows, lengths):
        m = self.plane.num_devices
        b = rows.shape[0]
        if b % m:
            pad = m - (b % m)
            rows = np.concatenate(
                [rows, np.zeros((pad, rows.shape[1]), rows.dtype)])
            lengths = np.concatenate([lengths, np.zeros(pad, lengths.dtype)])
        rows_d, lengths_d = self.plane.put(rows, lengths)
        ok, off, length, stats = self.plane(rows_d, lengths_d)
        self.last_stats = stats
        return ok, off, length
