"""loongcrash: acked-offset watermarks — the cross-restart durability spine.

The reader advances its checkpoint offset at READ time, so a ``kill -9``
silently loses everything between the last read and the last sink ack.
This module folds terminal delivery acknowledgements (sink ack, durable
spill, reason-tagged drop) back into per-(dev, inode) *contiguous*
watermarks: the checkpoint dump asks `durable_offset()` and persists the
low-watermark of acknowledged SOURCE bytes instead of the read offset.
After a crash the reader resumes at the watermark and re-reads only the
unacked window — at-least-once, never loss.

Shape (the chaos/ledger plane idiom): one module-global tracker, plain
dict/list state under one lock, no threads of its own.

  note_read(dev, ino, off, len, crc)   reader: span entered the pipeline
  note_fanout(group, n)                router: span needs n terminal acks
  ack_spans(spans) / ack_groups(...)   terminal boundaries: span delivered
  durable_offset(dev, ino, fallback)   checkpoint dump: acked frontier
  register_source(dev, ino, base)      file server: watermark authoritative

Sources the FileServer never registers (bare readers in unit tests) keep
the seed read-offset semantics — `durable_offset` falls back.  Pipelines
that destroy span identity before any terminal (aggregators, custom
sinks) are force-expired once a source's outstanding window overflows:
the watermark degrades to read-offset checkpointing (the pre-loongcrash
contract) instead of pinning the checkpoint forever; `forced_expirations`
counts every such give-up.

Acks are journaled (append + flush, no fsync — the page cache survives
SIGKILL; only power loss needs fsync, and the journal is a *duplicate
suppressor*, not a source of truth) so the recovery manager can suppress
re-reads of spans that were acked in the ack-to-checkpoint-dump window.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..models import EventGroupMetaKey

# per-source outstanding-span cap: beyond it the oldest spans are force-
# expired (watermark advances as if acked) so a non-acking pipeline cannot
# pin the checkpoint at its first unacked byte forever
MAX_OUTSTANDING_SPANS = 8192

Span = Tuple[int, int, int, int]   # (dev, inode, offset, length)


class _SourceState:
    __slots__ = ("base", "outstanding", "acked", "authoritative", "dumped")

    def __init__(self, base: int = 0):
        self.base = base              # contiguous acked/durable frontier
        # offset -> [length, crc32, refs]; refs = terminal acks still owed
        # (fanout to n flushers raises it to n before any copy can ack)
        self.outstanding: Dict[int, List[int]] = {}
        self.acked: List[List[int]] = []   # merged [start, end) beyond base
        self.authoritative = False    # register_source() ran (file server)
        self.dumped = -1              # last offset handed to a checkpoint dump


class AckWatermarkTracker:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: Dict[Tuple[int, int], _SourceState] = {}
        self._journal = None
        self._journal_path = ""
        self.forced_expirations = 0
        self.acked_spans_total = 0
        self.acked_bytes_total = 0

    # -- journal -------------------------------------------------------------

    def attach_journal(self, path: str) -> None:
        """Append acks to `path` from now on (recovery loads it first)."""
        with self._lock:
            self._close_journal()
            self._journal_path = path
            try:
                self._journal = open(path, "a")
            except OSError:
                self._journal = None

    def _close_journal(self) -> None:
        if self._journal is not None:
            try:
                self._journal.close()
            except OSError:
                pass
            self._journal = None

    def _journal_write(self, dev: int, ino: int, off: int, length: int,
                       crc: int) -> None:
        if self._journal is None:
            return
        try:
            self._journal.write(json.dumps(
                {"d": dev, "i": ino, "o": off, "l": length, "c": crc},
                separators=(",", ":")) + "\n")
            self._journal.flush()
        except (OSError, ValueError):
            self._close_journal()

    def compact_journal(self) -> None:
        """Rewrite the journal keeping only spans a restart could re-read:
        everything at or above each source's last *dumped* watermark (the
        checkpoint file is what decides where re-reading starts).  Runs at
        checkpoint-dump cadence so the journal stays a bounded window."""
        with self._lock:
            if self._journal is None or not self._journal_path:
                return
            keep: List[str] = []
            for (dev, ino), st in self._sources.items():
                # never dumped ⇒ a restart re-reads from 0 (or the restore
                # offset): keep the whole acked history for this source
                floor = st.dumped if st.dumped >= 0 else 0
                for start, end in st.acked:
                    if end > floor:
                        keep.append(json.dumps(
                            {"d": dev, "i": ino, "o": start,
                             "l": end - start, "c": 0},
                            separators=(",", ":")))
                # base-merged spans at/above the dumped floor must survive
                # too: they were acked but the checkpoint on disk is older
                if st.base > floor:
                    keep.append(json.dumps(
                        {"d": dev, "i": ino, "o": floor,
                         "l": st.base - floor, "c": 0},
                        separators=(",", ":")))
            tmp = self._journal_path + ".compact"
            try:
                self._close_journal()
                with open(tmp, "w") as f:
                    for line in keep:
                        f.write(line + "\n")
                    f.flush()
                os.replace(tmp, self._journal_path)
                self._journal = open(self._journal_path, "a")
            except OSError:
                self._journal = None

    # -- read-side hooks -----------------------------------------------------

    def register_source(self, dev: int, ino: int, base: int) -> None:
        """FileServer opened/restored a reader at `base`: the watermark for
        this source is authoritative from its first read — checkpoint dumps
        use the acked frontier, not the read offset."""
        with self._lock:
            st = self._sources.get((dev, ino))
            if st is None:
                st = self._sources[(dev, ino)] = _SourceState(base)
            elif not st.outstanding and not st.acked:
                st.base = base
            st.authoritative = True

    def note_read(self, dev: int, ino: int, off: int, length: int,
                  crc: int) -> None:
        if length <= 0 or not ino:
            return
        with self._lock:
            st = self._sources.get((dev, ino))
            if st is None:
                st = self._sources[(dev, ino)] = _SourceState(off)
            if off < st.base:
                # truncation / in-place rewrite: the old content's acks no
                # longer describe this file — restart the source's books
                auth = st.authoritative
                st = self._sources[(dev, ino)] = _SourceState(off)
                st.authoritative = auth
            entry = st.outstanding.get(off)
            if entry is not None:       # rollback re-read: idempotent
                entry[0] = length
                entry[1] = crc
                return
            st.outstanding[off] = [length, crc, 1]
            if len(st.outstanding) > MAX_OUTSTANDING_SPANS:
                self._force_expire(st)

    def _force_expire(self, st: _SourceState) -> None:
        """Outstanding window overflow: treat the oldest spans as acked so
        the watermark keeps moving (degrades to read-offset semantics for
        pipelines that never ack — the documented give-up, counted)."""
        while len(st.outstanding) > MAX_OUTSTANDING_SPANS // 2:
            off = min(st.outstanding)
            length, _, _ = st.outstanding.pop(off)
            self._merge_acked(st, off, off + length)
            self.forced_expirations += 1

    def note_fanout(self, group, n: int) -> None:
        """Router matched `n` flushers: the span owes n terminal acks.
        Must run BEFORE any flusher's send so a fast first copy cannot
        advance the watermark while the second is still in flight."""
        if n <= 1:
            return
        span = span_of(group)
        if span is None:
            return
        dev, ino, off, _ = span
        with self._lock:
            st = self._sources.get((dev, ino))
            if st is None:
                return
            entry = st.outstanding.get(off)
            if entry is not None:
                entry[2] = max(entry[2], n)

    # -- ack-side hooks ------------------------------------------------------

    def ack_spans(self, spans, force: bool = False) -> None:
        """Terminal delivery of `spans` (sink ack / durable spill / tagged
        drop).  `force` clears the span regardless of its fanout refcount —
        for terminals that end EVERY copy (pre-route drops, filtered-empty
        groups)."""
        if not spans:
            return
        with self._lock:
            for dev, ino, off, length in spans:
                st = self._sources.get((dev, ino))
                if st is None:
                    continue
                entry = st.outstanding.get(off)
                if entry is None:
                    continue    # unknown/stale ack (post-truncation): drop
                if not force:
                    entry[2] -= 1
                    if entry[2] > 0:
                        continue
                del st.outstanding[off]
                end = off + entry[0]
                self._merge_acked(st, off, end)
                self.acked_spans_total += 1
                self.acked_bytes_total += entry[0]
                self._journal_write(dev, ino, off, entry[0], entry[1])

    def _merge_acked(self, st: _SourceState, start: int, end: int) -> None:
        iv = st.acked
        lo = 0
        while lo < len(iv) and iv[lo][1] < start:
            lo += 1
        hi = lo
        while hi < len(iv) and iv[hi][0] <= end:
            start = min(start, iv[hi][0])
            end = max(end, iv[hi][1])
            hi += 1
        iv[lo:hi] = [[start, end]]
        # advance the contiguous frontier through everything now adjacent
        while iv and iv[0][0] <= st.base:
            if iv[0][1] > st.base:
                st.base = iv[0][1]
            iv.pop(0)

    # -- query side ----------------------------------------------------------

    def durable_offset(self, dev: int, ino: int, fallback: int) -> int:
        """Offset a checkpoint dump may persist for (dev, ino): the acked
        frontier for file-server-registered sources, the caller's read
        offset for everything else (bare readers keep seed semantics)."""
        with self._lock:
            st = self._sources.get((dev, ino))
            if st is None or not st.authoritative:
                return fallback
            out = min(st.base, fallback) if fallback >= 0 else st.base
            st.dumped = out
            return out

    def fully_acked(self, dev: int, ino: int) -> bool:
        with self._lock:
            st = self._sources.get((dev, ino))
            return st is None or not st.outstanding

    def outstanding_count(self, dev: int, ino: int) -> int:
        with self._lock:
            st = self._sources.get((dev, ino))
            return 0 if st is None else len(st.outstanding)

    def forget(self, dev: int, ino: int) -> None:
        """Source is gone for good (rotated reader fully drained+acked)."""
        with self._lock:
            self._sources.pop((dev, ino), None)

    def status(self) -> dict:
        with self._lock:
            return {
                "sources": len(self._sources),
                "outstanding_spans": sum(len(s.outstanding)
                                         for s in self._sources.values()),
                "acked_spans_total": self.acked_spans_total,
                "acked_bytes_total": self.acked_bytes_total,
                "forced_expirations": self.forced_expirations,
                "journal": self._journal_path or None,
            }

    def reset(self) -> None:
        with self._lock:
            self._sources.clear()
            self._close_journal()
            self._journal_path = ""
            self.forced_expirations = 0
            self.acked_spans_total = 0
            self.acked_bytes_total = 0


_tracker = AckWatermarkTracker()


def tracker() -> AckWatermarkTracker:
    return _tracker


# -- group/span plumbing ------------------------------------------------------

def span_of(group) -> Optional[Span]:
    """The (dev, inode, offset, length) SOURCE span riding `group`'s
    metadata since loongshard, or None for groups without file provenance
    (http inputs, aggregator rollups, disk-buffer replays)."""
    length = group.get_metadata(EventGroupMetaKey.LOG_FILE_LENGTH)
    if length is None:
        return None
    try:
        return (int(str(group.get_metadata(EventGroupMetaKey.LOG_FILE_DEV)
                        or 0)),
                int(str(group.get_metadata(EventGroupMetaKey.LOG_FILE_INODE)
                        or 0)),
                int(str(group.get_metadata(EventGroupMetaKey.LOG_FILE_OFFSET)
                        or 0)),
                int(str(length)))
    except (TypeError, ValueError):
        return None


def spans_of(groups) -> Tuple[Span, ...]:
    """Spans for a batch of groups — what SenderQueueItem.spans carries so
    the ack can fire at the item's terminal, long after serialization
    erased the groups themselves."""
    out = []
    for g in groups:
        span = span_of(g)
        if span is not None:
            out.append(span)
    return tuple(out)


# module-level conveniences (the call-site surface)

def note_read(dev: int, ino: int, off: int, length: int, crc: int) -> None:
    _tracker.note_read(dev, ino, off, length, crc)


def register_source(dev: int, ino: int, base: int) -> None:
    _tracker.register_source(dev, ino, base)


def note_fanout(group, n: int) -> None:
    _tracker.note_fanout(group, n)


def ack_spans(spans, force: bool = False) -> None:
    _tracker.ack_spans(spans, force=force)


def ack_groups(groups, force: bool = False) -> None:
    _tracker.ack_spans(spans_of(groups), force=force)


def durable_offset(dev: int, ino: int, fallback: int) -> int:
    return _tracker.durable_offset(dev, ino, fallback)


def fully_acked(dev: int, ino: int) -> bool:
    return _tracker.fully_acked(dev, ino)
