"""Singleton input-runner registry.

Reference: core/collection_pipeline/plugin/PluginRegistry.cpp:162-196 — the
registration matrix binding inputs to their singleton runners — and
InputFeedbackInterfaceRegistry (queue wakeup wiring). Round-1 wired every
runner by hand in Application.init/exit, which the VERDICT flagged as
bug-prone; with this registry a new singleton input runner declares itself
at import time and the application wires and stops it with ZERO edits.

Each entry: name, instance getter, stop method name, stop order (lower
stops first — self-monitor before data inputs so the drain can still ship
its telemetry).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List

from ..utils.logger import get_logger

log = get_logger("input_registry")


@dataclass
class _Entry:
    name: str
    instance: Callable[[], Any]
    stop_method: str = "stop"
    stop_order: int = 100


class InputRunnerRegistry:
    _entries: Dict[str, _Entry] = {}
    _lock = threading.Lock()

    @classmethod
    def register(cls, name: str, instance: Callable[[], Any],
                 stop_method: str = "stop", stop_order: int = 100) -> None:
        with cls._lock:
            cls._entries[name] = _Entry(name, instance, stop_method,
                                        stop_order)

    @classmethod
    def entries(cls) -> List[_Entry]:
        with cls._lock:
            return sorted(cls._entries.values(), key=lambda e: e.stop_order)

    @classmethod
    def wire_all(cls, process_queue_manager) -> None:
        """Hand every runner the process-queue manager (the watermark
        feedback boundary every input pushes through)."""
        for e in cls.entries():
            try:
                runner = e.instance()
            except Exception:  # noqa: BLE001 # loonglint: disable=unledgered-drop
                # a runner that failed to INSTANTIATE never read an event —
                # the continue abandons the registry entry, not a payload
                log.exception("input runner %s instantiation failed", e.name)
                continue
            if hasattr(runner, "process_queue_manager"):
                runner.process_queue_manager = process_queue_manager

    @classmethod
    def stop_all(cls) -> None:
        for e in cls.entries():
            try:
                runner = e.instance()
                getattr(runner, e.stop_method)()
            except Exception:  # noqa: BLE001
                log.exception("input runner %s stop failed", e.name)


def register_builtin_runners() -> None:
    """Declarative matrix of the built-in singleton runners (idempotent)."""
    from ..input.ebpf.server import EBPFServer
    from ..input.file.file_server import FileServer
    from ..input.forward import GrpcInputManager
    from ..input.host_monitor import HostMonitorInputRunner
    from ..input.prometheus.scraper import PrometheusInputRunner
    from ..monitor.self_monitor import SelfMonitorServer

    reg = InputRunnerRegistry.register
    reg("self_monitor", SelfMonitorServer.instance, stop_order=10)
    reg("host_monitor", HostMonitorInputRunner.instance, stop_order=20)
    reg("prometheus", PrometheusInputRunner.instance, stop_order=30)
    reg("ebpf", EBPFServer.instance, stop_order=40)
    reg("grpc_forward", GrpcInputManager.instance,
        stop_method="stop_all", stop_order=50)
    reg("file_server", FileServer.instance, stop_order=60)
