"""Per-sink three-state circuit breaker with spill-on-open degradation.

Unifies the scattered retry logic the reference spreads across
FlusherRunner backoff (FlusherRunner.cpp:133-141), AIMD sender-queue gates
and DiskBufferWriter spill into one explicit policy per sink:

  CLOSED     sends flow; consecutive failures and a sliding error-rate
             window are tracked.
  OPEN       tripped (streak >= failure_threshold, or error rate over
             `error_rate` with enough samples): callers stop burning the
             retry heap and route payloads to the disk buffer instead
             (spill-on-open).  An SINK_CIRCUIT_OPEN alarm fires on every
             open transition.
  HALF_OPEN  after `cooldown_s`, exactly one probe send is admitted.
             Success re-closes the breaker (and the owner replays spilled
             payloads); failure re-opens it and re-arms the cooldown.

State and transition counters export through monitor/metrics.py
(category "component", component "sink_circuit") so breaker behaviour is
visible in self-monitor output next to the chaos fault counters.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, List, Optional, Tuple

from .. import trace
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..monitor.metrics import MetricsRecord
from ..prof import flight
from ..utils.logger import get_logger

log = get_logger("circuit")


class BreakerState(enum.IntEnum):
    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


class SinkCircuitBreaker:
    """One breaker per sink (pipeline/flusher pair).

    Thread-safe; `allow_probe()` is consulted before a send, and exactly
    one of `on_success()` / `on_failure()` reports each send's outcome.
    `on_close` (if set) runs outside the lock whenever a half-open probe
    re-closes the breaker — owners hook disk-buffer replay there.

    The emit vocabulary (metric component, alarm type, flight/trace event
    prefix, degradation note) is class-level so other fault domains reuse
    the exact three-state machine with their own observability identity —
    loongmesh's per-chip lane breakers (ops/chip_lanes.ChipLaneBreaker)
    subclass this instead of re-implementing trip/probe/re-close.
    """

    COMPONENT = "sink_circuit"
    ALARM_TYPE = AlarmType.SINK_CIRCUIT_OPEN
    FLIGHT_PREFIX = "breaker"
    KIND = "sink"
    DEGRADE_NOTE = "degrading to disk buffer"

    def __init__(self, name: str,
                 failure_threshold: int = 5,
                 error_rate: float = 0.5,
                 window: int = 20,
                 min_samples: int = 8,
                 cooldown_s: float = 5.0,
                 on_close: Optional[Callable[[], None]] = None,
                 pipeline: str = ""):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.error_rate = float(error_rate)
        self.window = max(1, int(window))
        self.min_samples = max(1, int(min_samples))
        self.cooldown_s = float(cooldown_s)
        self.on_close = on_close
        self.pipeline = pipeline
        self._state = BreakerState.CLOSED
        self._streak = 0
        self._results: List[bool] = []        # sliding outcome window
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_started = 0.0
        # backstop: a probe whose outcome never reports (callback lost,
        # payload dropped without breaker feedback) must not wedge the
        # slot forever — after this long the probe counts as failed
        self.probe_timeout_s = max(30.0, 2 * self.cooldown_s)
        self._lock = threading.Lock()
        # transitions decided under the lock are EMITTED (trace event,
        # flight-recorder entry, alarm, on_close callback) outside it by
        # _emit() — the flight recorder must never be called under a held
        # lock (loonglint: blocking-under-lock, flight-record rule)
        self._pending_emits: List[Tuple[str, str]] = []
        self.metrics = MetricsRecord(
            category="component",
            labels={"component": self.COMPONENT, "sink": name})
        self._state_gauge = self.metrics.gauge("state")
        self._opened_total = self.metrics.counter("opened_total")
        self._reclosed_total = self.metrics.counter("reclosed_total")
        self._probes_total = self.metrics.counter("probes_total")
        self._spilled_total = self.metrics.counter("spilled_on_open_total")

    # -- queries -------------------------------------------------------------

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    def _expire_stuck_probe(self) -> None:
        """Lock held.  Release a probe slot whose outcome never arrived."""
        if self._probe_in_flight and \
                time.monotonic() - self._probe_started > self.probe_timeout_s:
            self._reopen("probe outcome never reported "
                         f"(> {self.probe_timeout_s:.0f}s)")

    def is_open(self) -> bool:
        """True while sends should degrade to the disk buffer: the breaker
        is OPEN, or HALF_OPEN with the single probe slot already taken."""
        with self._lock:
            if self._state is BreakerState.CLOSED:
                return False
            self._expire_stuck_probe()
            if self._state is BreakerState.HALF_OPEN:
                out = self._probe_in_flight
            else:
                out = time.monotonic() - self._opened_at < self.cooldown_s
        self._emit()
        return out

    def allow_probe(self) -> bool:
        """True when a send may proceed: always in CLOSED; in OPEN only
        once the cooldown elapsed (transitioning to HALF_OPEN and claiming
        the single probe slot); in HALF_OPEN only if the slot is free."""
        out = False
        with self._lock:
            if self._state is BreakerState.CLOSED:
                return True
            self._expire_stuck_probe()
            if self._state is BreakerState.OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown_s:
                    self._state = BreakerState.HALF_OPEN
                    self._state_gauge.set(float(BreakerState.HALF_OPEN))
                    self._probe_in_flight = True
                    self._probe_started = time.monotonic()
                    self._probes_total.add(1)
                    self._pending_emits.append(("half_open", ""))
                    out = True
            elif not self._probe_in_flight:
                self._probe_in_flight = True
                self._probe_started = time.monotonic()
                self._probes_total.add(1)
                out = True
        self._emit()
        return out

    def note_spilled(self, n: int = 1) -> None:
        self._spilled_total.add(n)

    # -- outcomes ------------------------------------------------------------

    def on_success(self) -> None:
        with self._lock:
            self._record(True)
            self._streak = 0
            if self._state is not BreakerState.CLOSED:
                # an OPEN-state success can only be a probe (or a straggler
                # from before the trip) — both prove the sink works again
                self._state = BreakerState.CLOSED
                self._probe_in_flight = False
                self._results.clear()
                self._state_gauge.set(float(BreakerState.CLOSED))
                self._reclosed_total.add(1)
                self._pending_emits.append(("close", ""))
        self._emit()

    def on_inconclusive(self) -> None:
        """The send ended without a health signal (payload dropped as
        invalid, callback itself failed): record no sample, but release a
        held probe slot by re-arming the cooldown — a wedged slot would
        otherwise block every future probe."""
        with self._lock:
            if self._state is BreakerState.HALF_OPEN and \
                    self._probe_in_flight:
                self._reopen("probe outcome inconclusive")
            elif self._state is BreakerState.OPEN:
                self._probe_in_flight = False
        self._emit()

    def on_failure(self) -> None:
        with self._lock:
            self._record(False)
            self._streak += 1
            if self._state is BreakerState.HALF_OPEN:
                self._reopen("half-open probe failed")
            elif self._state is BreakerState.OPEN:
                self._probe_in_flight = False
            else:
                trip_streak = self._streak >= self.failure_threshold
                trip_rate = (len(self._results) >= self.min_samples
                             and (self._results.count(False)
                                  / len(self._results) > self.error_rate))
                if trip_streak or trip_rate:
                    self._reopen(
                        f"{self._streak} consecutive failures" if trip_streak
                        else f"error rate over {self.error_rate:.0%} "
                             f"in last {len(self._results)} sends")
        self._emit()

    # -- internals (call with lock held) -------------------------------------

    def _record(self, ok: bool) -> None:
        self._results.append(ok)
        if len(self._results) > self.window:
            del self._results[0]

    def mark_deleted(self) -> None:
        """Retire this breaker's metric record (owner stopped or its
        sink's queue was deleted) — the record must not outlive it in
        WriteMetrics."""
        self.metrics.mark_deleted()

    def _reopen(self, why: str) -> None:
        self._state = BreakerState.OPEN
        self._opened_at = time.monotonic()
        self._probe_in_flight = False
        self._streak = 0
        self._state_gauge.set(float(BreakerState.OPEN))
        self._opened_total.add(1)
        self._pending_emits.append(("open", why))

    def _emit(self) -> None:
        """Deliver transition side effects (trace event, flight-recorder
        entry, alarm, on_close) decided under the lock — outside it."""
        # unlocked pre-check: transitions are rare, and every send pays
        # is_open()/allow_probe() — the common no-transition path must not
        # buy a second lock cycle.  Appends happen only under the lock and
        # each appender drains via its own _emit, so a stale-empty read
        # here just defers delivery to the thread that appended.
        if not self._pending_emits:
            return
        with self._lock:
            if not self._pending_emits:
                return
            emits, self._pending_emits = self._pending_emits, []
        pre = self.FLIGHT_PREFIX
        for kind, why in emits:
            if kind == "open":
                if trace.is_active():
                    trace.event(f"{pre}.open", sink=self.name, why=why)
                flight.record(f"{pre}.open", sink=self.name, why=why)
                log.warning("%s circuit %s opened: %s", self.KIND,
                            self.name, why)
                AlarmManager.instance().send_alarm(
                    self.ALARM_TYPE,
                    f"{self.KIND} {self.name} circuit opened: {why}; "
                    f"{self.DEGRADE_NOTE}",
                    AlarmLevel.ERROR, pipeline=self.pipeline)
            elif kind == "half_open":
                if trace.is_active():
                    trace.event(f"{pre}.half_open", sink=self.name)
                flight.record(f"{pre}.half_open", sink=self.name)
            else:
                if trace.is_active():
                    trace.event(f"{pre}.close", sink=self.name)
                flight.record(f"{pre}.close", sink=self.name)
                log.info("%s circuit %s re-closed", self.KIND, self.name)
                if self.on_close is not None:
                    self.on_close()
