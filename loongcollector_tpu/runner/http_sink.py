"""HttpSink: the network dispatch thread.

Reference: core/runner/sink/http/HttpSink.cpp — a dedicated thread around a
curl_multi event loop (:91,124); completed responses dispatch back to the
flusher's OnSendDone, decrement in-flight counts and feed queues.

Implementation: a small worker pool over http.client (stdlib; the image has
no external HTTP deps) with the same completion contract.
"""

from __future__ import annotations

import http.client
import queue as _queue
import threading
from typing import Callable, Optional, Tuple
from urllib.parse import urlparse

from ..utils.logger import get_logger

log = get_logger("http_sink")


class HttpSink:
    def __init__(self, workers: int = 4):
        self.workers = workers
        self._queue: _queue.Queue = _queue.Queue()
        self._threads = []
        self._running = False

    def init(self) -> None:
        self._running = True
        for i in range(self.workers):
            t = threading.Thread(target=self._run, name=f"http-sink-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def add_request(self, request, on_done: Callable[[int, bytes], None]) -> None:
        """request: flusher.HttpRequest; on_done(status, body) runs on a sink
        worker thread (status 0 ⇒ network error)."""
        self._queue.put((request, on_done))

    def pending(self) -> int:
        return self._queue.qsize()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            request, on_done = item
            status, body = self._execute(request)
            try:
                on_done(status, body)
            except Exception:  # noqa: BLE001
                log.exception("on_done callback failed")

    @staticmethod
    def _execute(request) -> Tuple[int, bytes]:
        try:
            u = urlparse(request.url)
            conn_cls = (http.client.HTTPSConnection if u.scheme == "https"
                        else http.client.HTTPConnection)
            conn = conn_cls(u.netloc, timeout=request.timeout)
            path = u.path or "/"
            if u.query:
                path += "?" + u.query
            conn.request(request.method, path, body=request.body,
                         headers=request.headers)
            resp = conn.getresponse()
            body = resp.read()
            status = resp.status
            conn.close()
            return status, body
        except Exception as e:  # noqa: BLE001 - any transport failure = retryable
            return 0, str(e).encode()
