"""HttpSink: the network dispatch thread.

Reference: core/runner/sink/http/HttpSink.cpp — a dedicated thread around a
curl_multi event loop (:91,124); completed responses dispatch back to the
flusher's OnSendDone, decrement in-flight counts and feed queues.

Implementation: a small worker pool over http.client (stdlib; the image has
no external HTTP deps) with the same completion contract.
"""

from __future__ import annotations

import http.client
import queue as _queue
import threading
from typing import Callable, Optional, Tuple
from urllib.parse import urlparse

from ..utils.logger import get_logger

log = get_logger("http_sink")


class HttpSink:
    def __init__(self, workers: int = 4):
        self.workers = workers
        self._queue: _queue.Queue = _queue.Queue()
        self._threads = []
        self._running = False
        # per-worker persistent connections keyed by (scheme, netloc) —
        # the reference reuses connections via curl_multi (HttpSink.cpp:91);
        # per-thread maps need no locking
        self._local = threading.local()

    def init(self) -> None:
        self._running = True
        for i in range(self.workers):
            t = threading.Thread(target=self._run, name=f"http-sink-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        for _ in self._threads:
            self._queue.put(None)
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def add_request(self, request, on_done: Callable[[int, bytes], None]) -> None:
        """request: flusher.HttpRequest; on_done(status, body) runs on a sink
        worker thread (status 0 ⇒ network error)."""
        self._queue.put((request, on_done))

    def pending(self) -> int:
        return self._queue.qsize()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            request, on_done = item
            status, body = self._execute(request)
            try:
                on_done(status, body)
            except Exception:  # noqa: BLE001
                log.exception("on_done callback failed")

    def _get_conn(self, scheme: str, netloc: str, timeout: float):
        """Returns (conn, reused)."""
        pool = getattr(self._local, "conns", None)
        if pool is None:
            pool = self._local.conns = {}
        key = (scheme, netloc)
        conn = pool.get(key)
        reused = conn is not None
        if conn is None:
            conn_cls = (http.client.HTTPSConnection if scheme == "https"
                        else http.client.HTTPConnection)
            conn = conn_cls(netloc, timeout=timeout)
            pool[key] = conn
        conn.timeout = timeout
        if reused and conn.sock is not None:
            # http.client applies timeout only at connect(); a reused
            # socket must be re-armed or it keeps the FIRST request's value
            conn.sock.settimeout(timeout)
        return conn, reused

    def _drop_conn(self, scheme: str, netloc: str) -> None:
        pool = getattr(self._local, "conns", None)
        if pool is None:
            return
        conn = pool.pop((scheme, netloc), None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _execute(self, request) -> Tuple[int, bytes]:
        # _execute must NEVER raise: an escaped exception kills the worker
        # thread and silently wedges every flusher sharing the sink.
        # Method-preserving redirects (307/308) are followed a few hops —
        # Doris stream-load answers every FE request with a 307 to a BE.
        url = request.url
        for _ in range(3):
            status, body, location = self._execute_once(url, request)
            if status in (307, 308) and location:
                url = location
                continue
            return status, body
        return status, body

    def _execute_once(self, url: str, request):
        try:
            u = urlparse(url)
            path = u.path or "/"
            if u.query:
                path += "?" + u.query
        except ValueError as e:
            return 0, str(e).encode(), None
        # one reconnect retry, but ONLY when the SEND on a kept-alive
        # connection failed (the server closed it — standard keep-alive
        # race; nothing was processed). A failure after the request went
        # out (slow/lost response) must NOT re-send: the server may have
        # ingested the batch, and duplication is the flusher's call.
        while True:
            reused = False
            sent = False
            try:
                conn, reused = self._get_conn(u.scheme, u.netloc,
                                              request.timeout)
                conn.request(request.method, path, body=request.body,
                             headers=request.headers)
                sent = True
                resp = conn.getresponse()
                body = resp.read()
                location = resp.getheader("Location")
                if resp.will_close:
                    self._drop_conn(u.scheme, u.netloc)
                return resp.status, body, location
            except Exception as e:  # noqa: BLE001 - transport = retryable
                self._drop_conn(u.scheme, u.netloc)
                if not reused or sent:
                    return 0, str(e).encode(), None
