"""HttpSink: the network dispatch thread — a curl_multi-class event loop.

Reference: core/runner/sink/http/HttpSink.cpp — ONE dedicated thread around
a curl_multi event loop (:91,124) drives every in-flight transfer for every
flusher concurrently; completed responses dispatch back to the flusher's
OnSendDone, decrement in-flight counts and feed queues.

This implementation is the same shape on stdlib asyncio: a single event-loop
thread multiplexes all connections (TLS included), with

  * per-destination persistent connection pools (keep-alive reuse),
  * per-destination in-flight caps — a stalled or slow destination queues
    only its OWN transfers and can never starve other sinks (the failure
    mode of the previous worker-pool design: N slow requests = dead sink),
  * stale keep-alive defense: idle pooled connections that received FIN/EOF
    while parked are discarded at acquire time (reader.at_eof()), and a
    write failure on a reused connection retries once on a fresh one —
    a completed send is NEVER retried here (duplication is the flusher's
    call, same contract as before),
  * method-preserving redirects (307/308) followed a few hops — Doris
    stream-load answers every FE request with a 307 to a BE,
  * completion callbacks run on a separate dispatcher thread so a slow
    OnSendDone cannot stall network progress.

Public contract unchanged: init()/stop()/add_request(request, on_done)/
pending(); on_done(status, body) with status 0 ⇒ network error.
"""

from __future__ import annotations

import asyncio
import queue as _queue
import ssl
import threading
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import urlparse

from .. import chaos
from ..utils.logger import get_logger

log = get_logger("http_sink")

_MAX_REDIRECTS = 3

FP_SEND = chaos.register_point("http_sink.send")


class _Dest:
    """Per-destination state: connection pool + concurrency gate."""

    __slots__ = ("sem", "idle")

    def __init__(self, limit: int):
        self.sem = asyncio.Semaphore(limit)
        self.idle: List[Tuple[asyncio.StreamReader, asyncio.StreamWriter]] = []


class HttpSink:
    def __init__(self, workers: int = 4):
        # `workers` is kept from the pool-era API; it now bounds PER-DEST
        # concurrent transfers (the event loop itself has no thread limit)
        self.per_dest = max(1, workers)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._cb_queue: _queue.Queue = _queue.Queue()
        self._cb_thread: Optional[threading.Thread] = None
        self._running = False
        self._pending = 0
        self._pending_lock = threading.Lock()
        self._dests: Dict[Tuple[str, str, int], _Dest] = {}
        self._ssl_ctx = ssl.create_default_context()

    # ------------------------------------------------------------- lifecycle

    def init(self) -> None:
        self._running = True
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run_loop,
                                        name="http-sink", daemon=True)
        self._thread.start()
        self._cb_thread = threading.Thread(target=self._run_callbacks,
                                           name="http-sink-cb", daemon=True)
        self._cb_thread.start()

    def stop(self) -> None:
        self._running = False
        loop = self._loop
        if loop is not None:
            # drain first: in-flight transfers get a grace window to finish
            # (FlusherRunner's exit-spill skips in_flight items on the
            # expectation that their pending send may yet succeed) — only
            # stragglers are cancelled
            try:
                fut = asyncio.run_coroutine_threadsafe(
                    self._drain(5.0), loop)
                fut.result(timeout=8)
            except Exception as e:  # noqa: BLE001 — loop may already be closing
                log.warning("http sink drain interrupted at stop: %r", e)
            loop.call_soon_threadsafe(self._shutdown_loop)
            if self._thread is not None:
                self._thread.join(timeout=5)
        self._cb_queue.put(None)
        if self._cb_thread is not None:
            self._cb_thread.join(timeout=5)
        self._thread = None
        self._cb_thread = None
        self._loop = None

    def add_request(self, request,
                    on_done: Callable[[int, bytes], None]) -> None:
        """request: flusher.HttpRequest; on_done(status, body) runs on the
        callback-dispatch thread (status 0 ⇒ network error)."""
        loop = self._loop
        if loop is None or not self._running:
            self._cb_queue.put((on_done, 0, b"http sink not running"))
            return
        with self._pending_lock:
            self._pending += 1
        try:
            loop.call_soon_threadsafe(
                lambda: loop.create_task(self._transfer(request, on_done)))
        except RuntimeError:  # loop already closed (stop race)
            self._complete(on_done, 0, b"http sink stopped")

    def pending(self) -> int:
        return self._pending

    # ------------------------------------------------------------ loop guts

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_forever()
        finally:
            try:
                self._loop.close()
            except Exception:  # noqa: BLE001
                pass

    async def _drain(self, timeout: float) -> None:
        tasks = [t for t in asyncio.all_tasks()
                 if t is not asyncio.current_task()]
        if tasks:
            await asyncio.wait(tasks, timeout=timeout)

    def _shutdown_loop(self) -> None:
        for task in asyncio.all_tasks(self._loop):
            task.cancel()
        for dest in self._dests.values():
            for _, writer in dest.idle:
                try:
                    writer.close()
                except Exception:  # noqa: BLE001
                    pass
            dest.idle.clear()
        self._loop.call_soon(self._loop.stop)

    def _run_callbacks(self) -> None:
        while True:
            item = self._cb_queue.get()
            if item is None:
                return
            on_done, status, body = item
            try:
                on_done(status, body)
            except Exception:  # noqa: BLE001
                log.exception("on_done callback failed")

    def _complete(self, on_done, status: int, body: bytes) -> None:
        with self._pending_lock:
            self._pending -= 1
        self._cb_queue.put((on_done, status, body))

    # -------------------------------------------------------- HTTP/1.1 client

    async def _transfer(self, request, on_done) -> None:
        status, body = 0, b""
        try:
            # injected faults surface as status 0 + error body — the exact
            # shape a refused connect / RST produces, so flushers classify
            # them through their real retry verdicts
            chaos.faultpoint(FP_SEND, exc=ConnectionError)
            url = request.url
            for _ in range(_MAX_REDIRECTS):
                status, body, location = await self._execute_once(url, request)
                if status in (307, 308) and location:
                    url = location
                    continue
                break
        except asyncio.CancelledError:
            body = b"http sink stopped"
        except Exception as e:  # noqa: BLE001 — transfer must never escape
            status, body = 0, repr(e).encode()
        self._complete(on_done, status, body)

    def _dest(self, key: Tuple[str, str, int]) -> _Dest:
        dest = self._dests.get(key)
        if dest is None:
            dest = self._dests[key] = _Dest(self.per_dest)
        return dest

    async def _execute_once(self, url: str, request):
        try:
            u = urlparse(url)
            host = u.hostname or ""
            port = u.port or (443 if u.scheme == "https" else 80)
            path = u.path or "/"
            if u.query:
                path += "?" + u.query
        except ValueError as e:
            return 0, str(e).encode(), None
        key = (u.scheme, host, port)
        dest = self._dest(key)
        async with dest.sem:
            # one reconnect retry, ONLY when the SEND on a kept-alive
            # connection failed (server closed it — the keep-alive race;
            # nothing was processed).  A failure after the request went out
            # must NOT re-send: the server may have ingested the batch.
            for attempt in (0, 1):
                reused = True
                sent = False
                reader = writer = None
                try:
                    # the keep-alive retry (attempt 1) must use a FRESH
                    # connection — a second stale idle one would waste the
                    # one retry the no-resend-after-send rule allows
                    got = self._pop_idle(dest) if attempt == 0 else None
                    if got is None:
                        reused = False
                        reader, writer = await asyncio.wait_for(
                            asyncio.open_connection(
                                host, port,
                                ssl=self._ssl_ctx
                                if u.scheme == "https" else None),
                            timeout=request.timeout)
                    else:
                        reader, writer = got
                    head = self._request_head(u, host, port, path, request)
                    writer.write(head)
                    if request.body:
                        writer.write(request.body)
                    await asyncio.wait_for(writer.drain(),
                                           timeout=request.timeout)
                    sent = True
                    status, body, location, will_close = \
                        await asyncio.wait_for(
                            self._read_response(reader, request.method),
                            timeout=request.timeout)
                    if will_close:
                        writer.close()
                    else:
                        dest.idle.append((reader, writer))
                    return status, body, location
                except Exception as e:  # noqa: BLE001 transport = retryable
                    if writer is not None:
                        try:
                            writer.close()
                        except Exception:  # noqa: BLE001
                            pass
                    if not reused or sent or attempt == 1:
                        return 0, repr(e).encode(), None

    def _pop_idle(self, dest: _Dest):
        """Reuse a parked connection, discarding any that died while idle
        (EOF/FIN arrives asynchronously — at_eof() sees it without a read)."""
        while dest.idle:
            reader, writer = dest.idle.pop()
            if reader.at_eof() or writer.is_closing():
                try:
                    writer.close()
                except Exception:  # noqa: BLE001
                    pass
                continue
            return reader, writer
        return None

    @staticmethod
    def _request_head(u, host: str, port: int, path: str, request) -> bytes:
        default_port = 443 if u.scheme == "https" else 80
        host_hdr = host if port == default_port else f"{host}:{port}"
        lines = [f"{request.method} {path} HTTP/1.1",
                 f"Host: {host_hdr}"]
        hdrs = {k.lower(): (k, v) for k, v in (request.headers or {}).items()}
        if "host" in hdrs:
            lines[1] = f"Host: {hdrs.pop('host')[1]}"
        if "content-length" not in hdrs and request.method not in ("GET",
                                                                  "HEAD"):
            body_len = len(request.body) if request.body else 0
            lines.append(f"Content-Length: {body_len}")
        if "connection" not in hdrs:
            lines.append("Connection: keep-alive")
        for k, v in hdrs.values():
            lines.append(f"{k}: {v}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    @staticmethod
    async def _read_response(reader: asyncio.StreamReader, method: str):
        # absorb interim 1xx responses (Early Hints, 100-continue echoes):
        # they are NOT the final response — returning one would desync the
        # kept-alive connection (http.client did this absorption too)
        for _ in range(8):
            status_line = await reader.readline()
            if not status_line:
                raise ConnectionResetError("EOF before status line")
            parts = status_line.split(None, 2)
            status = int(parts[1])
            headers: Dict[bytes, bytes] = {}
            while True:
                line = await reader.readline()
                if not line:
                    raise ConnectionResetError("EOF inside response headers")
                if line in (b"\r\n", b"\n"):
                    break
                k, _, v = line.partition(b":")
                headers[k.strip().lower()] = v.strip()
            if status >= 200 or status == 101:   # 101 upgrade = final here
                break
        te = headers.get(b"transfer-encoding", b"").lower()
        clen = headers.get(b"content-length")
        body = b""
        has_len = False
        if method == "HEAD" or status in (204, 304) or status < 200:
            has_len = True
        elif b"chunked" in te:
            has_len = True
            chunks = []
            while True:
                size_line = await reader.readline()
                if not size_line:
                    # EOF mid-stream is NOT the terminal chunk — a silently
                    # truncated body must never return as success
                    raise ConnectionResetError("EOF inside chunked body")
                size = int(size_line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    while True:  # trailers
                        t = await reader.readline()
                        if not t:
                            raise ConnectionResetError(
                                "EOF inside chunked trailers")
                        if t in (b"\r\n", b"\n"):
                            break
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readexactly(2)  # chunk CRLF
            body = b"".join(chunks)
        elif clen is not None:
            has_len = True
            body = await reader.readexactly(int(clen))
        else:
            body = await reader.read()  # until EOF (HTTP/1.0-style)
        conn_hdr = headers.get(b"connection", b"").lower()
        will_close = (conn_hdr == b"close"
                      or status_line.startswith(b"HTTP/1.0")
                      or not has_len)
        location_b = headers.get(b"location")
        location = location_b.decode("latin-1") if location_b else None
        return status, body, location, will_close
