"""FlusherRunner: drains sender queues into the HTTP sink.

Reference: core/runner/FlusherRunner.cpp — single thread (:168); pops
available items (rate + AIMD gates consulted inside the queues), dispatches
by sink type (:219), exponential backoff on failure (100 ms → 10 s,
:133-141), global send-byte rate limit (:202-204).

On top of the reference shape, each sink gets a three-state circuit
breaker (runner/circuit.py): a sink that fails persistently OPENs its
breaker, and instead of spinning payloads through the retry heap the
runner routes them straight to the DiskBufferWriter (spill-on-open
degradation).  When the half-open probe succeeds the breaker re-closes
and the runner replays the spilled payloads back through the live
flusher — the unified resilience policy ISSUE 2 asks for.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import heapq

from .. import prof, trace
from ..monitor import ledger, slo
from ..monitor.metrics import MetricsRecord
from ..pipeline.queue.limiter import RateLimiter
from ..pipeline.queue.sender_queue import (SenderQueueItem,
                                           SenderQueueManager)
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..utils import flags
from ..utils.logger import get_logger
from . import ack_watermark
from .circuit import BreakerState, SinkCircuitBreaker
from .http_sink import HttpSink

log = get_logger("flusher_runner")

RETRY_BASE_S = 0.1
RETRY_MAX_S = 10.0
MAX_TRY_BEFORE_SPILL = 20  # persistent failure → disk buffer (if configured)

# reference FlusherRunner.cpp:223-227 enable_full_drain_mode: spill what the
# exit drain budget could not flush instead of dropping it
flags.DEFINE_FLAG_BOOL("enable_full_drain_mode",
                       "spill undrained payloads to disk on exit", True)

# observe-only handle for /debug/status (monitor/exposition.py): the live
# runner's breaker states without constructing anything — the same idiom
# as runner/processor_runner.py's _active_runner
_active_runner = None


class FlusherRunner:
    def __init__(self, sender_queue_manager: SenderQueueManager,
                 http_sink: Optional[HttpSink] = None,
                 max_bytes_per_sec: int = 0, disk_buffer=None,
                 breaker_failure_threshold: int = 5,
                 breaker_error_rate: float = 0.5,
                 breaker_cooldown_s: float = 5.0):
        self.sqm = sender_queue_manager
        self.http_sink = http_sink
        self.disk_buffer = disk_buffer
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.rate_limiter = RateLimiter(max_bytes_per_sec)
        self._retry_heap = []
        self._retry_lock = threading.Lock()
        self._retry_thread: Optional[threading.Thread] = None
        self.breaker_failure_threshold = breaker_failure_threshold
        self.breaker_error_rate = breaker_error_rate
        self.breaker_cooldown_s = breaker_cooldown_s
        self._breakers: Dict[int, SinkCircuitBreaker] = {}
        self._breaker_lock = threading.Lock()
        # flushers seen at spill time, keyed by spill identity — the
        # resolver for breaker-close replay (the Application's periodic
        # replay handles flushers this runner never met)
        self._spilled_flushers: Dict[Tuple[str, str, str], object] = {}
        self._replay_pending = threading.Event()
        self.metrics = MetricsRecord(category="runner",
                                     labels={"runner": "flusher"})
        self.out_items = self.metrics.counter("out_items_total")
        self.out_bytes = self.metrics.counter("out_size_bytes")
        self.spilled_items = self.metrics.counter("spilled_items_total")
        # dispatch → on_done latency per send attempt, and how long items
        # sat in their sender queue before this dispatch picked them up
        self.sink_rtt_hist = self.metrics.histogram("sink_rtt_seconds")
        self.sender_wait_hist = self.metrics.histogram(
            "sender_queue_wait_seconds")

    def init(self) -> None:
        global _active_runner
        self._running = True
        _active_runner = self
        self._thread = threading.Thread(target=self._run, name="flusher-runner",
                                        daemon=True)
        self._thread.start()

    # -- circuit breakers ----------------------------------------------------

    def breaker_for(self, item: SenderQueueItem) -> SinkCircuitBreaker:
        key = item.queue_key
        with self._breaker_lock:
            br = self._breakers.get(key)
            if br is None:
                flusher = item.flusher
                ident = (flusher.spill_identity() if flusher is not None
                         else {})
                name = (f"{ident.get('pipeline', '')}/"
                        f"{ident.get('flusher_type', 'unknown')}")
                br = SinkCircuitBreaker(
                    name,
                    failure_threshold=self.breaker_failure_threshold,
                    error_rate=self.breaker_error_rate,
                    cooldown_s=self.breaker_cooldown_s,
                    on_close=self._replay_pending.set,
                    pipeline=ident.get("pipeline", ""))
                self._breakers[key] = br
            return br

    def breakers(self) -> Dict[int, SinkCircuitBreaker]:
        with self._breaker_lock:
            return dict(self._breakers)

    def gc_breakers(self) -> int:
        """loongtenant: every hot reload retires the old generation's
        sender queues, but their breakers (and metric records) would
        accumulate in ``_breakers`` forever under config churn.  Drop and
        retire breakers whose queue no longer exists — queue keys are
        never reused, so a dropped key can't come back.  Runs on the
        runner loop's probe cadence."""
        with self._breaker_lock:
            keys = list(self._breakers)
        dead_keys = [k for k in keys if self.sqm.get_queue(k) is None]
        if not dead_keys:
            return 0
        dead = []
        with self._breaker_lock:
            for k in dead_keys:
                br = self._breakers.pop(k, None)
                if br is not None:
                    dead.append(br)
        for br in dead:
            br.mark_deleted()
        return len(dead)

    # -- lifecycle -----------------------------------------------------------

    def stop(self, drain: bool = True, timeout: float = 5.0) -> None:
        global _active_runner
        if _active_runner is self:
            _active_runner = None
        if drain:
            deadline = time.monotonic() + timeout
            while not self.sqm.all_empty() and time.monotonic() < deadline:
                time.sleep(0.05)
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._exit_spill()
        finally:
            # retire this runner's metric records (and its breakers') AFTER
            # the exit spill — its spilled_items_total increments must land
            # on a record that is still exportable — so repeated
            # construct/stop cycles never accumulate live records
            self.metrics.mark_deleted()
            for br in self.breakers().values():
                br.mark_deleted()

    def _exit_spill(self) -> None:
        # exit spill: whatever could not drain in the budget persists to disk
        # (reference FlusherRunner.cpp:223-227 full-drain/spill on exit).
        # Items still in-flight in the HTTP sink are skipped — their pending
        # send may yet succeed, and spilling them would double-deliver.
        if self.disk_buffer is None \
                or not flags.get_flag("enable_full_drain_mode"):
            return
        # the retry heap first: its items are normally still queued (and get
        # spilled below), but items whose queue was deleted mid-backoff are
        # reachable ONLY from the heap — dropping the heap would drop them
        with self._retry_lock:
            heap_items = [entry[2] for entry in self._retry_heap]
            self._retry_heap.clear()
        for q in list(self.sqm._queues.values()):
            with q._lock:
                items = [i for i in q._items
                         if not getattr(i, "in_flight", False)]
            for item in items:
                self._spill_item(item)
        for item in heap_items:
            if getattr(item, "in_flight", False):
                continue
            if self.sqm.get_queue(item.queue_key) is not None:
                continue        # still queued: the loop above owned it
            self._spill_item(item)

    def _run(self) -> None:
        prof.push_marker("worker", "flusher-runner")
        try:
            self._run_inner()
        finally:
            prof.pop_marker()

    def _run_inner(self) -> None:
        last_probe_replay = 0.0
        while self._running:
            if self._replay_pending.is_set():
                self._replay_pending.clear()
                self._replay_spilled()
            # a fully-spilled sink has no queued traffic left to drive the
            # half-open probe: when any breaker is off-CLOSED and a cooldown
            # has passed, pull spilled payloads back as probe traffic (a
            # failing probe just re-spills them)
            now = time.monotonic()
            if now - last_probe_replay >= self.breaker_cooldown_s:
                last_probe_replay = now
                # reload churn hygiene rides the same cadence: breakers
                # of deleted sender queues retire instead of accumulating
                self.gc_breakers()
                if (self.disk_buffer is not None
                        and any(br.state is not BreakerState.CLOSED
                                for br in self.breakers().values())):
                    self._replay_spilled()
            items = self.sqm.get_available_items()
            if not items:
                # backlog-aware hand-off (loongcolumn): a sender-queue push
                # wakes this loop immediately; the 20 ms timeout is only
                # the deadline fallback driving retry/replay cadences on
                # an idle agent
                self.sqm.wait_for_data(0.02)
                continue
            for item in items:
                if not self.rate_limiter.is_valid_to_pop():
                    self._requeue_later(item)
                    continue
                self.rate_limiter.post_pop(len(item.data))
                self._dispatch(item)

    def _release_limiters(self, item: SenderQueueItem) -> None:
        q = self.sqm.get_queue(item.queue_key)
        if q is not None:
            for cl in q.concurrency_limiters:
                cl.on_done()

    def _requeue_later(self, item: SenderQueueItem) -> None:
        self._release_limiters(item)
        q = self.sqm.get_queue(item.queue_key)
        if q is not None:
            q.reset_item_status(item)

    # -- spill / replay ------------------------------------------------------

    def _spill_item(self, item: SenderQueueItem, breaker=None) -> bool:
        """Route one undeliverable item to the disk buffer, freeing its
        queue slot.  False when spilling is impossible (no buffer, buffer
        full, exactly-once item) — the caller falls back to backoff."""
        flusher = item.flusher
        if (self.disk_buffer is None or flusher is None
                or "eo_cp" in item.tag):
            return False
        identity = flusher.spill_identity()
        if not self.disk_buffer.spill(item, identity):
            return False
        # durable on disk IS a terminal for the SOURCE span: the replay
        # path owns delivery from here, the checkpoint may advance
        ack_watermark.ack_spans(item.spans)
        if slo.is_on():
            slo.observe_stamps(self._ledger_pipeline(item), item.stamps,
                               slo.OUTCOME_SPILL)
        self.spilled_items.add(1)
        if breaker is not None:
            breaker.note_spilled()
        self._spilled_flushers[(identity.get("pipeline", ""),
                                identity.get("flusher_type", ""),
                                identity.get("plugin_id", ""))] = flusher
        self.sqm.remove_item(item)
        return True

    def spill_queue(self, queue) -> int:
        """loongtenant reload drain fallback: spill a retiring sender
        queue's idle payloads to the disk buffer so a wedged sink cannot
        pin an old pipeline generation forever.  Items are CLAIMED
        (status → SENDING) under the queue lock first, so the dispatch
        loop can never pick one up concurrently — a double terminal
        (spill + send_ok for the same events) would read as a negative
        conservation residual.  Returns how many items spilled; items the
        buffer refuses are restored to IDLE for the normal retry path."""
        if self.disk_buffer is None:
            return 0
        # backoff-parked items first: a wedged sink's payloads spend most
        # of their time in the retry HEAP (status SENDING while they wait
        # out the backoff) — claim them out of the heap so the retry loop
        # can never redispatch one we are spilling
        heap_claimed = []
        with self._retry_lock:
            keep = []
            for entry in self._retry_heap:
                item = entry[2]
                if item.queue_key == queue.key \
                        and not getattr(item, "in_flight", False):
                    heap_claimed.append(item)
                else:
                    keep.append(entry)
            if heap_claimed:
                self._retry_heap[:] = keep
                heapq.heapify(self._retry_heap)
        claimed = queue.claim_idle_items()
        spilled = 0
        for item in heap_claimed:
            if self._spill_item(item):
                spilled += 1
            else:
                self._backoff_retry(item)   # buffer refused: keep retrying
        for item in claimed:
            if self._spill_item(item):
                spilled += 1
            else:
                queue.reset_item_status(item)
        return spilled

    def _resolve_spilled(self, identity: dict):
        key = (identity.get("pipeline", ""),
               identity.get("flusher_type", ""),
               identity.get("plugin_id", ""))
        flusher = self._spilled_flushers.get(key)
        if flusher is None:
            return None
        # a pipeline swap deletes the sender queue: replaying into the
        # orphaned queue object would strand the payload AND delete its
        # file — drop the stale registry entry and keep the file for the
        # Application's resolver (which knows the live pipelines)
        q = self.sqm.get_queue(getattr(flusher, "queue_key", -1))
        if q is None or q is not getattr(flusher, "sender_queue", None):
            self._spilled_flushers.pop(key, None)
            return None
        return flusher

    def _replay_spilled(self) -> None:
        if self.disk_buffer is None:
            return
        try:
            self.disk_buffer.replay(self._resolve_spilled)
        except Exception:  # noqa: BLE001
            log.exception("breaker-close replay failed; files kept")

    # -- dispatch ------------------------------------------------------------

    def _ledger_pipeline(self, item: SenderQueueItem) -> str:
        q = self.sqm.get_queue(item.queue_key)
        if q is not None:
            return q.pipeline_name
        flusher = item.flusher
        if flusher is not None:
            return flusher.spill_identity().get("pipeline", "")
        return ""

    def _dispatch(self, item: SenderQueueItem) -> None:
        flusher = item.flusher
        if flusher is None or self.http_sink is None:
            # nowhere to send: the payload leaves the queue terminally
            if ledger.is_on():
                ledger.record(self._ledger_pipeline(item), ledger.B_DROP,
                              item.event_cnt, len(item.data), tag="no_sink")
            ack_watermark.ack_spans(item.spans)
            if slo.is_on():
                slo.observe_stamps(self._ledger_pipeline(item), item.stamps,
                                   slo.OUTCOME_DROP)
            self._release_limiters(item)
            self.sqm.remove_item(item)
            return
        breaker = self.breaker_for(item)
        if not breaker.allow_probe():
            # open circuit: degrade to disk instead of burning retries
            self._release_limiters(item)
            if not self._spill_item(item, breaker):
                self._backoff_retry(item)
            return
        try:
            request = flusher.build_request(item)
        except Exception:  # noqa: BLE001
            log.exception("build_request failed; backing off")
            self._release_limiters(item)
            breaker.on_failure()
            if breaker.is_open() and self._spill_item(item, breaker):
                return
            self._backoff_retry(item)
            return
        item.in_flight = True
        self.sender_wait_hist.observe(
            max(0.0, time.monotonic() - item.enqueue_time))
        # the send-attempt stopwatch rides the item's last_send_time slot
        # (its reference meaning); _on_done turns it into the sink RTT
        item.last_send_time = time.monotonic()
        tracer = trace.active_tracer()
        sp = (tracer.child_or_sampled(f"sink:{breaker.name}", "sink.send",
                                      attrs={"sink": breaker.name,
                                             "try_count": item.try_count})
              if tracer is not None else None)
        self.http_sink.add_request(
            request, lambda status, body, it=item, sp=sp:
            self._on_done(it, status, body, sp))

    def _on_done(self, item: SenderQueueItem, status: int, body: bytes,
                 span=None) -> None:
        item.in_flight = False
        if item.last_send_time:
            self.sink_rtt_hist.observe(
                max(0.0, time.monotonic() - item.last_send_time))
        if span is not None:
            span.set_attr("status", status)
            span.end("ok" if 200 <= status < 300 else "error")
        flusher = item.flusher
        q = self.sqm.get_queue(item.queue_key)
        breaker = self.breaker_for(item)
        verdict = "drop"
        cb_failed = True
        try:
            verdict = flusher.on_send_done(item, status, body)
            cb_failed = False
        except Exception:  # noqa: BLE001
            log.exception("on_send_done failed")
        if q is not None:
            for cl in q.concurrency_limiters:
                cl.on_done()
                if verdict == "ok":
                    cl.on_success()
                elif verdict == "retry_slow":
                    # quota exceeded: collapse concurrency hard (AIMD slow
                    # path), regardless of raw status code
                    cl.on_fail(slow=True)
                elif verdict == "retry":
                    cl.on_fail(slow=(status == 429))
        if verdict == "ok":
            breaker.on_success()
        elif verdict in ("retry", "retry_slow"):
            breaker.on_failure()
        elif not cb_failed and status > 0:
            # permanent rejection with a real HTTP status: the payload is
            # dropped but the ENDPOINT answered — that is a healthy sink
            # (and a probe in flight must not wedge the slot)
            breaker.on_success()
        else:
            # callback blew up / status unknown: no health signal either
            # way — release a held probe slot without recording a sample
            breaker.on_inconclusive()
        if verdict == "retry_slow":
            AlarmManager.instance().send_alarm(
                AlarmType.SEND_QUOTA_EXCEED,
                f"quota exceeded (status {status})", AlarmLevel.WARNING)
        elif verdict == "retry":
            AlarmManager.instance().send_alarm(
                AlarmType.SEND_FAIL, f"send failed (status {status}); "
                "backing off", AlarmLevel.WARNING)
        elif verdict == "drop":
            # the exception fallback also lands here: the payload IS lost
            # either way, but operators must not read a local flusher bug
            # as a backend rejection
            AlarmManager.instance().send_alarm(
                AlarmType.DISCARD_DATA,
                ("payload dropped: flusher callback failed "
                 if cb_failed else
                 "payload dropped after permanent rejection ")
                + f"(status {status})", AlarmLevel.ERROR)
        if verdict in ("retry", "retry_slow"):
            # one failed attempt: the item stays inflight (retry heap or
            # spill), never double-counted — send_fail is informational.
            # is_on() guard: _ledger_pipeline takes the sqm lock, which a
            # disabled ledger must never pay for on the retry path
            if ledger.is_on():
                ledger.record(self._ledger_pipeline(item),
                              ledger.B_SEND_FAIL,
                              item.event_cnt, len(item.data))
            # spill-on-open: an open breaker (or plain try-count exhaustion)
            # moves the payload to disk and frees the queue slot
            # (reference DiskBufferWriter semantics)
            if (breaker.is_open()
                    or item.try_count >= MAX_TRY_BEFORE_SPILL):
                if self._spill_item(item, breaker):
                    return
            self._backoff_retry(item)
            return
        if ledger.is_on():
            if verdict == "ok":
                ledger.record(self._ledger_pipeline(item), ledger.B_SEND_OK,
                              item.event_cnt, len(item.data))
            else:
                # permanent rejection / callback failure: terminal discard
                ledger.record(self._ledger_pipeline(item), ledger.B_DROP,
                              item.event_cnt, len(item.data),
                              tag=("callback_failed" if cb_failed
                                   else "permanent_reject"))
        # sink accepted (or permanently rejected) the payload: terminal
        # for its SOURCE spans either way — the watermark moves
        ack_watermark.ack_spans(item.spans)
        if slo.is_on():
            slo.observe_stamps(self._ledger_pipeline(item), item.stamps,
                               slo.OUTCOME_SEND_OK if verdict == "ok"
                               else slo.OUTCOME_DROP)
        self.out_items.add(1)
        self.out_bytes.add(len(item.data))
        self.sqm.remove_item(item)

    def _backoff_retry(self, item: SenderQueueItem) -> None:
        """Exponential backoff (100 ms → 10 s, reference FlusherRunner.cpp
        :133-141) via a single shared timer heap — no thread per retry."""
        delay = min(RETRY_BASE_S * (2 ** min(item.try_count, 8)), RETRY_MAX_S)
        if trace.is_active():
            trace.event("retry.backoff", try_count=item.try_count,
                        delay_s=delay)
        with self._retry_lock:
            heapq.heappush(self._retry_heap,
                           (time.monotonic() + delay, id(item), item))
            if self._retry_thread is None or not self._retry_thread.is_alive():
                self._retry_thread = threading.Thread(
                    target=self._retry_loop, name="flusher-retry", daemon=True)
                self._retry_thread.start()

    def _retry_loop(self) -> None:
        while True:
            with self._retry_lock:
                if not self._retry_heap:
                    return
                due, _, item = self._retry_heap[0]
                now = time.monotonic()
                if due <= now:
                    heapq.heappop(self._retry_heap)
                else:
                    item = None
                    wait = due - now
            if item is None:
                time.sleep(min(wait, 0.5))
                continue
            q = self.sqm.get_queue(item.queue_key)
            if q is not None:
                q.reset_item_status(item)
            elif not self._spill_item(item):
                # queue deleted while the item waited out its backoff
                # (pipeline swap) AND the spill refused (no buffer / full):
                # the payload is gone — ledger the loss, don't hide it
                if ledger.is_on():
                    ledger.record(self._ledger_pipeline(item), ledger.B_DROP,
                                  item.event_cnt, len(item.data),
                                  tag="retry_orphaned")
                ack_watermark.ack_spans(item.spans)
                if slo.is_on():
                    slo.observe_stamps(self._ledger_pipeline(item),
                                       item.stamps, slo.OUTCOME_DROP)
