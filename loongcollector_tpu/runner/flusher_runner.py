"""FlusherRunner: drains sender queues into the HTTP sink.

Reference: core/runner/FlusherRunner.cpp — single thread (:168); pops
available items (rate + AIMD gates consulted inside the queues), dispatches
by sink type (:219), exponential backoff on failure (100 ms → 10 s,
:133-141), global send-byte rate limit (:202-204).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import heapq

from ..monitor.metrics import MetricsRecord
from ..pipeline.queue.limiter import RateLimiter
from ..pipeline.queue.sender_queue import (SenderQueueItem, SenderQueueManager,
                                           SendingStatus)
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..utils.logger import get_logger
from .http_sink import HttpSink

log = get_logger("flusher_runner")

RETRY_BASE_S = 0.1
RETRY_MAX_S = 10.0
MAX_TRY_BEFORE_SPILL = 20  # persistent failure → disk buffer (if configured)


class FlusherRunner:
    def __init__(self, sender_queue_manager: SenderQueueManager,
                 http_sink: Optional[HttpSink] = None,
                 max_bytes_per_sec: int = 0, disk_buffer=None):
        self.sqm = sender_queue_manager
        self.http_sink = http_sink
        self.disk_buffer = disk_buffer
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.rate_limiter = RateLimiter(max_bytes_per_sec)
        self._retry_heap = []
        self._retry_lock = threading.Lock()
        self._retry_thread: Optional[threading.Thread] = None
        self.metrics = MetricsRecord(category="runner",
                                     labels={"runner": "flusher"})
        self.out_items = self.metrics.counter("out_items_total")
        self.out_bytes = self.metrics.counter("out_size_bytes")

    def init(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._run, name="flusher-runner",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain: bool = True, timeout: float = 5.0) -> None:
        if drain:
            deadline = time.monotonic() + timeout
            while not self.sqm.all_empty() and time.monotonic() < deadline:
                time.sleep(0.05)
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        # exit spill: whatever could not drain in the budget persists to disk
        # (reference FlusherRunner.cpp:223-227 full-drain/spill on exit).
        # Items still in-flight in the HTTP sink are skipped — their pending
        # send may yet succeed, and spilling them would double-deliver.
        if self.disk_buffer is not None:
            for q in list(self.sqm._queues.values()):
                with q._lock:
                    items = [i for i in q._items
                             if not getattr(i, "in_flight", False)
                             and "eo_cp" not in i.tag]
                for item in items:
                    flusher = item.flusher
                    if flusher is None:
                        continue
                    if self.disk_buffer.spill(item, flusher.spill_identity()):
                        q.remove(item)

    def _run(self) -> None:
        while self._running:
            items = self.sqm.get_available_items()
            if not items:
                time.sleep(0.02)
                continue
            for item in items:
                if not self.rate_limiter.is_valid_to_pop():
                    self._requeue_later(item)
                    continue
                self.rate_limiter.post_pop(len(item.data))
                self._dispatch(item)

    def _release_limiters(self, item: SenderQueueItem) -> None:
        q = self.sqm.get_queue(item.queue_key)
        if q is not None:
            for cl in q.concurrency_limiters:
                cl.on_done()

    def _requeue_later(self, item: SenderQueueItem) -> None:
        self._release_limiters(item)
        q = self.sqm.get_queue(item.queue_key)
        if q is not None:
            q.reset_item_status(item)

    def _dispatch(self, item: SenderQueueItem) -> None:
        flusher = item.flusher
        if flusher is None or self.http_sink is None:
            self._release_limiters(item)
            self.sqm.remove_item(item)
            return
        try:
            request = flusher.build_request(item)
        except Exception:  # noqa: BLE001
            log.exception("build_request failed; backing off")
            self._release_limiters(item)
            self._backoff_retry(item)
            return
        item.in_flight = True
        self.http_sink.add_request(
            request, lambda status, body, it=item: self._on_done(it, status, body))

    def _on_done(self, item: SenderQueueItem, status: int, body: bytes) -> None:
        item.in_flight = False
        flusher = item.flusher
        q = self.sqm.get_queue(item.queue_key)
        verdict = "drop"
        cb_failed = True
        try:
            verdict = flusher.on_send_done(item, status, body)
            cb_failed = False
        except Exception:  # noqa: BLE001
            log.exception("on_send_done failed")
        if q is not None:
            for cl in q.concurrency_limiters:
                cl.on_done()
                if verdict == "ok":
                    cl.on_success()
                elif verdict == "retry_slow":
                    # quota exceeded: collapse concurrency hard (AIMD slow
                    # path), regardless of raw status code
                    cl.on_fail(slow=True)
                elif verdict == "retry":
                    cl.on_fail(slow=(status == 429))
        if verdict == "retry_slow":
            AlarmManager.instance().send_alarm(
                AlarmType.SEND_QUOTA_EXCEED,
                f"quota exceeded (status {status})", AlarmLevel.WARNING)
        elif verdict == "retry":
            AlarmManager.instance().send_alarm(
                AlarmType.SEND_FAIL, f"send failed (status {status}); "
                "backing off", AlarmLevel.WARNING)
        elif verdict == "drop":
            # the exception fallback also lands here: the payload IS lost
            # either way, but operators must not read a local flusher bug
            # as a backend rejection
            AlarmManager.instance().send_alarm(
                AlarmType.DISCARD_DATA,
                ("payload dropped: flusher callback failed "
                 if cb_failed else
                 "payload dropped after permanent rejection ")
                + f"(status {status})", AlarmLevel.ERROR)
        if verdict in ("retry", "retry_slow"):
            if (self.disk_buffer is not None
                    and item.try_count >= MAX_TRY_BEFORE_SPILL
                    and flusher is not None
                    and "eo_cp" not in item.tag):
                # persistent failure: spill to disk and free the queue slot
                # (reference DiskBufferWriter semantics)
                if self.disk_buffer.spill(item, flusher.spill_identity()):
                    self.sqm.remove_item(item)
                    return
            self._backoff_retry(item)
            return
        self.out_items.add(1)
        self.out_bytes.add(len(item.data))
        self.sqm.remove_item(item)

    def _backoff_retry(self, item: SenderQueueItem) -> None:
        """Exponential backoff (100 ms → 10 s, reference FlusherRunner.cpp
        :133-141) via a single shared timer heap — no thread per retry."""
        delay = min(RETRY_BASE_S * (2 ** min(item.try_count, 8)), RETRY_MAX_S)
        with self._retry_lock:
            heapq.heappush(self._retry_heap,
                           (time.monotonic() + delay, id(item), item))
            if self._retry_thread is None or not self._retry_thread.is_alive():
                self._retry_thread = threading.Thread(
                    target=self._retry_loop, name="flusher-retry", daemon=True)
                self._retry_thread.start()

    def _retry_loop(self) -> None:
        while True:
            with self._retry_lock:
                if not self._retry_heap:
                    return
                due, _, item = self._retry_heap[0]
                now = time.monotonic()
                if due <= now:
                    heapq.heappop(self._retry_heap)
                else:
                    item = None
                    wait = due - now
            if item is None:
                time.sleep(min(wait, 0.5))
                continue
            q = self.sqm.get_queue(item.queue_key)
            if q is not None:
                q.reset_item_status(item)
