"""Disk buffering of failed/exiting send payloads.

Reference: core/plugin/flusher/sls/DiskBufferWriter.h:56,92 — serialized
payloads that cannot be sent (endpoint down, agent exiting) spill to disk
and replay on recovery; FlusherRunner spills SLS items at exit
(FlusherRunner.cpp:223-227, enable_full_drain_mode).

Format: one file per payload under <dir>/<tenant>/buffer_<ts>_<seq>.lcb
with a JSON header line (flusher identity + raw size + metadata) followed
by the payload bytes — ENCRYPTED at rest when a PayloadCipher is attached
(reference DiskBufferWriter.h:56 treats buffer-file encryption as
production-critical; a host-level reader of the spill directory must not
recover log content).  Plaintext files from older runs still replay.
Replay re-enqueues through the live flusher of the same pipeline/plugin
identity when it exists.

loongtenant namespace isolation: spills land in a per-pipeline
subdirectory (``<dir>/<sanitized pipeline name>/``; legacy files in the
root keep replaying) with a per-tenant byte quota — ``max_bytes`` split
evenly over the namespaces present — so one tenant filling the buffer
can refuse only ITS OWN spills, and ``pending()`` interleaves
namespaces round-robin so one tenant's deep backlog cannot starve every
other tenant's replay behind the per-round ``limit``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, List, Optional, Tuple

from .. import chaos, trace
from ..chaos import ChaosFault
from ..monitor import ledger
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..prof import flight
from ..pipeline.queue.sender_queue import SenderQueueItem
from ..utils.logger import get_logger

log = get_logger("disk_buffer")

MAX_BUFFER_BYTES = 512 * 1024 * 1024

FP_WRITE = chaos.register_point("disk_buffer.write")
FP_REPLAY = chaos.register_point("disk_buffer.replay")

_NS_SAFE = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."


def _namespace_of(pipeline: str) -> str:
    """Filesystem-safe per-tenant namespace ("" = legacy root for
    unattributed payloads).  Collisions after sanitisation merge two
    tenants' QUOTAS, never their bytes (each file's header still names
    its true pipeline)."""
    if not pipeline:
        return ""
    ns = "".join(c if c in _NS_SAFE else "_" for c in pipeline)[:120]
    # ".."/"." are path traversal, a ".bad"-style suffix is quarantine
    # vocabulary — none may become a directory name
    if ns in (".", "..") or ns.startswith("."):
        ns = "_" + ns.lstrip(".")
    return ns or "_"


class DiskBufferWriter:
    def __init__(self, directory: str,
                 max_bytes: int = MAX_BUFFER_BYTES,
                 cipher=None):
        self.directory = directory
        self.max_bytes = max_bytes
        self.cipher = cipher  # utils.payload_crypto.PayloadCipher or None
        self._seq = 0
        self._lock = threading.Lock()
        self._run_id = uuid.uuid4().hex[:8]  # filenames unique across restarts
        # lazily-initialized running byte totals, keyed per tenant
        # namespace ("" = legacy root files); None until first scanned
        self._totals = None  # type: Optional[dict]
        # loongledger sidecar: path -> (pipeline, event_cnt) for files THIS
        # process spilled (and thus ledgered as B_SPILL).  A quarantined
        # file whose header is unreadable still settles its ledger balance
        # through this map; files from earlier runs are not in it and were
        # never counted, so their quarantine records nothing
        self._spill_ledger: dict = {}

    # -- namespace accounting ------------------------------------------------

    def _ns_of_path(self, path: str) -> str:
        parent = os.path.dirname(os.path.abspath(path))
        root = os.path.abspath(self.directory)
        return "" if parent == root else os.path.basename(parent)

    def _ensure_totals(self) -> dict:
        """Lock held.  Per-namespace byte totals of files at rest."""
        if self._totals is None:
            totals: dict = {}
            for ns, paths in self._pending_by_ns().items():
                for path in paths:
                    try:
                        totals[ns] = totals.get(ns, 0) \
                            + os.path.getsize(path)
                    except OSError:
                        pass
            self._totals = totals
        return self._totals

    def _tenant_cap(self, totals: dict, ns: str) -> int:
        """One namespace's byte quota: the global cap split evenly over
        the namespaces present (this one included).  A single tenant
        keeps the whole buffer — exactly the pre-tenant behaviour."""
        n = len(set(totals) | {ns})
        return self.max_bytes if n <= 1 else self.max_bytes // n

    def tenant_usage(self) -> dict:
        """Per-namespace bytes at rest (observe-only; "" = legacy root)."""
        with self._lock:
            return dict(self._ensure_totals())

    # -- write --------------------------------------------------------------

    def spill(self, item: SenderQueueItem, identity: dict) -> bool:
        """Persist one sender item.  identity: whatever the flusher needs to
        reclaim the payload (pipeline name, flusher type, plugin id...)."""
        ns = _namespace_of(identity.get("pipeline", ""))
        ns_dir = (os.path.join(self.directory, ns) if ns
                  else self.directory)
        os.makedirs(ns_dir, exist_ok=True)
        with self._lock:
            totals = self._ensure_totals()
            used = totals.get(ns, 0)
            cap = self._tenant_cap(totals, ns)
            if sum(totals.values()) + len(item.data) > self.max_bytes:
                # the GLOBAL cap still binds: per-tenant quotas divide the
                # buffer, they never let the sum overshoot it (tenants
                # arriving one at a time would otherwise stack shrinking
                # caps up to max_bytes * H(n))
                log.warning("disk buffer full; dropping payload (%d bytes)",
                            len(item.data))
                return False
            if used + len(item.data) > cap:
                # per-tenant quota: only THIS tenant's spill refuses —
                # other namespaces keep their headroom untouched
                log.warning(
                    "disk buffer tenant quota exceeded for %r "
                    "(%d + %d > %d); dropping payload",
                    ns or "<root>", used, len(item.data), cap)
                return False
            totals[ns] = used + len(item.data)
            self._seq += 1
            name = (f"buffer_{int(time.time())}_{self._run_id}"
                    f"_{self._seq}.lcb")
        header = dict(identity)
        header["raw_size"] = item.raw_size
        header["enqueue_time"] = time.time()
        # event provenance rides the spill so replay restores event-unit
        # accounting (0 = unknown, e.g. a pre-ledger item)
        header["event_cnt"] = getattr(item, "event_cnt", 0)
        payload = item.data
        if self.cipher is not None:
            payload = self.cipher.encrypt(payload)
            header["enc"] = "hmac-ctr-v1"
        path = os.path.join(ns_dir, name)
        tmp = path + ".tmp"
        try:
            # injected OSError rides the real write-failure path below;
            # a "corrupt" decision garbles the file AFTER the atomic
            # rename (corrupt-at-rest — replay must quarantine, not abort)
            decision = chaos.faultpoint(FP_WRITE, exc=OSError)
            # crash-safe: temp file + fsync + atomic rename — a crash or
            # power cut mid-spill leaves either the complete old state or
            # a stray .tmp (ignored by pending()), never a torn .lcb
            with open(tmp, "wb") as f:
                f.write(json.dumps(header).encode() + b"\n")
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            if decision is not None and decision.action == chaos.ACTION_CORRUPT:
                with open(path, "r+b") as f:
                    f.write(b"\x00chaos-corrupt\x00")
        except OSError as e:
            log.error("disk buffer write failed: %s", e)
            with self._lock:
                self._note_removed(path, len(item.data))
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        if trace.is_active():
            trace.event("disk_buffer.spill",
                        pipeline=header.get("pipeline", ""),
                        flusher=header.get("flusher_type", ""),
                        nbytes=len(item.data))
        flight.record("disk_buffer.spill",
                      pipeline=header.get("pipeline", ""),
                      flusher=header.get("flusher_type", ""),
                      nbytes=len(item.data))
        if ledger.is_on():
            # spill is a conservation SINK: the events are safely at rest;
            # a later replay credits them back as a source
            ledger.record(header.get("pipeline", ""), ledger.B_SPILL,
                          header["event_cnt"], len(item.data))
            with self._lock:
                self._spill_ledger[path] = (header.get("pipeline", ""),
                                            header["event_cnt"])
        return True

    # -- read / replay ------------------------------------------------------

    def _walk_files(self, suffix: str) -> dict:
        """{namespace: sorted matching paths} over the root (legacy ""
        files) and every tenant subdirectory — the one traversal both
        pending() and quarantined() ride."""
        out: dict = {}
        try:
            entries = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for e in entries:
            full = os.path.join(self.directory, e)
            if e.endswith(suffix):
                out.setdefault("", []).append(full)
            elif os.path.isdir(full):
                try:
                    files = sorted(os.path.join(full, f)
                                   for f in os.listdir(full)
                                   if f.endswith(suffix))
                except OSError:
                    continue
                if files:
                    out[e] = files
        return out

    def _pending_by_ns(self) -> dict:
        return self._walk_files(".lcb")

    def pending(self) -> List[str]:
        """All buffered payload paths, interleaved ROUND-ROBIN across
        tenant namespaces (oldest-first within each): replay's per-round
        ``limit`` then advances every tenant's backlog instead of
        serving one deep tenant exclusively."""
        by_ns = self._pending_by_ns()
        lanes = [by_ns[ns] for ns in sorted(by_ns)]
        out: List[str] = []
        i = 0
        while lanes:
            lanes = [lane for lane in lanes if i < len(lane)]
            for lane in lanes:
                out.append(lane[i])
            i += 1
        return out

    def read(self, path: str) -> Optional[Tuple[dict, bytes]]:
        status, header, payload = self._read_classified(path)
        return (header, payload) if status == "ok" else None

    def _read_classified(self, path: str):
        """('ok', header, payload) | ('corrupt', None, None) — structurally
        broken, safe to delete | ('locked', None, None) — encrypted but not
        currently decryptable (missing/wrong key): KEEP the file, the key
        may come back."""
        try:
            with open(path, "rb") as f:
                header = json.loads(f.readline())
                payload = f.read()
        except (OSError, ValueError):
            return "corrupt", None, None
        if not isinstance(header, dict):
            return "corrupt", None, None
        if header.get("enc") == "hmac-ctr-v1":
            if self.cipher is None:
                log.error("encrypted buffer file but no cipher configured; "
                          "keeping for later: %s", path)
                return "locked", None, None
            payload = self.cipher.decrypt(payload)
            if payload is None:   # wrong key or tampered file
                log.error("buffer file failed authentication; keeping: %s",
                          path)
                return "locked", None, None
        return "ok", header, payload

    def replay(self, resolve: Callable[[dict], Optional[object]],
               limit: int = 100) -> int:
        """Re-enqueue up to `limit` buffered payloads.  `resolve(identity)`
        returns the live flusher (with .sender_queue and .queue_key) or None
        if the pipeline no longer exists (payload is kept for later)."""
        count = 0
        # scan ALL pending files but count only replayed ones toward the
        # limit — otherwise >limit unresolvable old files would starve every
        # newer payload forever
        for path in self.pending():
            if count >= limit:
                break
            try:
                chaos.faultpoint(FP_REPLAY)
            except ChaosFault:
                continue     # transient replay fault: file stays for later
            status, header, payload = self._read_classified(path)
            if status == "corrupt":
                # quarantine, don't delete: a malformed file is evidence
                # (torn write from a crash, bit rot, injected corruption)
                # and must neither abort the replay loop nor vanish
                self._quarantine(path)
                continue
            if status == "locked":   # undecryptable today ≠ deletable
                continue
            flusher = resolve(header)
            if flusher is None or flusher.sender_queue is None:
                continue
            item = SenderQueueItem(payload, header.get("raw_size", len(payload)),
                                   flusher=flusher,
                                   queue_key=flusher.queue_key,
                                   event_cnt=int(header.get("event_cnt", 0)))
            if flusher.sender_queue.push(item) is False:
                # target refused (replay adapter at capacity): the file is
                # the only copy — keep it for a later round
                continue
            if ledger.is_on():
                # replay is a conservation SOURCE: the events re-enter the
                # live send path and will terminate again (send_ok, a
                # re-spill, or a drop)
                ledger.record(header.get("pipeline", ""), ledger.B_REPLAY,
                              item.event_cnt, len(payload))
            with self._lock:
                self._spill_ledger.pop(path, None)
            self._remove(path)
            count += 1
            if trace.is_active():
                trace.event("disk_buffer.replay",
                            pipeline=header.get("pipeline", ""),
                            flusher=header.get("flusher_type", ""),
                            nbytes=len(payload))
            flight.record("disk_buffer.replay",
                          pipeline=header.get("pipeline", ""),
                          flusher=header.get("flusher_type", ""),
                          nbytes=len(payload))
        if count:
            log.info("replayed %d buffered payloads", count)
        return count

    def _quarantine(self, path: str) -> None:
        """Rename a malformed buffer file to `.lcb.bad` (out of pending())
        and alarm — operators can inspect or purge, replay moves on."""
        try:
            size = os.path.getsize(path)
            os.replace(path, path + ".bad")
        except OSError as e:
            log.error("quarantine of %s failed: %s", path, e)
            return
        with self._lock:
            self._note_removed(path, size)
            spilled = self._spill_ledger.pop(path, None)
        if spilled is not None and ledger.is_on():
            # the file was ledgered as B_SPILL when this process wrote it:
            # credit it back out of the buffer (replay, tag=quarantine) and
            # retire the events terminally at the quarantine boundary — the
            # residual stays zero while `quarantine` names the loss bucket
            pipeline, events = spilled
            ledger.record(pipeline, ledger.B_REPLAY, events, size,
                          tag="quarantine")
            ledger.record(pipeline, ledger.B_QUARANTINE, events, size)
        log.error("malformed buffer file quarantined: %s.bad", path)
        if trace.is_active():
            trace.event("disk_buffer.quarantine", nbytes=size)
        AlarmManager.instance().send_alarm(
            AlarmType.SECONDARY_READ_WRITE,
            f"malformed disk-buffer file quarantined ({size} bytes)",
            AlarmLevel.ERROR)

    def quarantined(self) -> List[str]:
        by_ns = self._walk_files(".lcb.bad")
        return [p for ns in sorted(by_ns) for p in by_ns[ns]]

    def _note_removed(self, path: str, size: int) -> None:
        """Lock held: a file left its namespace — release quota bytes.
        A namespace that drained to zero leaves the table entirely, so a
        long-gone tenant does not keep shrinking every LIVE tenant's
        quota share forever."""
        if self._totals is not None:
            ns = self._ns_of_path(path)
            left = max(0, self._totals.get(ns, 0) - size)
            if left:
                self._totals[ns] = left
            else:
                self._totals.pop(ns, None)

    def _remove(self, path: str) -> None:
        try:
            size = os.path.getsize(path)
            os.remove(path)
        except OSError:
            return
        with self._lock:
            self._note_removed(path, size)
