"""ProcessorRunner: the sharded multi-worker processing engine (loongshard).

Reference: core/runner/ProcessorRunner.cpp — N worker threads (default 1,
app_config/AppConfig.cpp:58) pop from the process-queue manager (priority RR),
find the owning pipeline, run Process then Send (:90-189); thread 0 also
pumps batch timeout flushes (:109-112); producer API PushQueue with bounded
retries (:72-88).

loongshard (ISSUE 4) makes thread_count real without giving up ordering:

* ``thread_count == 1`` keeps the reference shape — one worker popping the
  process-queue manager directly.
* ``thread_count > 1`` adds a dispatch loop that pops the queue manager and
  routes every group to a fixed worker by affinity hash on
  (process queue key, ``__source__`` tag).  All groups of one source stream
  land on one worker, and each worker is a single thread that sends groups
  in pop order — per-source ordering is preserved while distinct sources
  (and distinct pipelines) process in parallel.  The hash is CRC32, stable
  across runs and processes (PYTHONHASHSEED-proof), so a replayed soak
  shards identically.
* Worker inboxes are small and bounded: when a worker falls behind, the
  dispatcher blocks on its inbox, stops popping, and the bounded process
  queues fill to their high watermark — the same feedback chain as before,
  one hop longer.

TPU note — the async device data plane (SURVEY §7 step 4), now streaming
(loongstream, ISSUE 6): each worker owns ONE WorkerLane — a FIFO ring
holding up to ``LOONG_STREAM_DEPTH - 1`` groups whose device work is in
flight.  The worker dispatches group N+1 (host pre-processing + ring-slot
pack + async kernel dispatch via Pipeline.process_begin), then advances the
ring: the OLDEST pending group (N-depth+1) materialises and sends while the
device computes the newer ones — pack/H2D of N+1 overlaps compute of N and
span-return of N-1.  The auto-tuner's flush deadline bounds how long a
group may ride the ring, so trickle traffic keeps interactive latency.
Device back-pressure is the DevicePlane in-flight byte budget: when the
device stalls, dispatch blocks, the worker stops consuming, its inbox
fills, the dispatcher stops popping, and the bounded process queues
feedback-block the inputs.  Every worker registers a budget-relief hook
bound to ITS lane, so a worker waiting for budget always completes the
oldest overlapped group it owns (no-deadlock invariant, per lane; FIFO, so
relief never reorders sends).
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from collections import deque
from typing import List, Optional, Tuple

from .. import prof, trace
from ..models import EventGroupMetaKey, PipelineEventGroup
from ..monitor import ledger, slo
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..monitor.metrics import MetricsRecord
from ..ops import chip_lanes
from ..ops.device_plane import (current_tenant, note_host_backlog,
                                set_budget_relief, set_thread_tenant)
from ..ops.device_stream import auto_tuner
from . import ack_watermark
from ..prof import flight
from ..pipeline.batch.timeout_flush_manager import TimeoutFlushManager
from ..pipeline.queue.process_queue_manager import (RUN_MAX_GROUPS,
                                                    ProcessQueueManager)
from ..utils import flags
from ..utils.logger import get_logger

log = get_logger("processor_runner")

BATCH_FLUSH_INTERVAL_S = 1.0

# loongshard default: scale past one worker out of the box, but never spawn
# more shards than the host can run (the reference default of 1 mirrored the
# pre-shard engine; docs/performance.md)
DEFAULT_PROCESS_THREADS = max(2, min(4, os.cpu_count() or 2))

flags.DEFINE_FLAG_INT32("process_thread_count",
                        "processor runner worker shards",
                        DEFAULT_PROCESS_THREADS)

ENV_THREADS = "LOONG_PROCESS_THREADS"

# observe-only handle for the self-monitor (monitor/runtime_stats.py):
# the live runner's shard state without constructing anything
_active_runner = None


def resolve_thread_count(env=os.environ) -> int:
    """Active worker count: ``LOONG_PROCESS_THREADS`` wins over the
    ``process_thread_count`` flag (itself overridable by app config and
    ``LOONG_PROCESS_THREAD_COUNT``); anything invalid or < 1 falls back,
    and the result is always >= 1."""
    raw = env.get(ENV_THREADS)
    if raw is not None:
        try:
            n = int(raw)
            if n >= 1:
                return n
            log.warning("%s=%r below 1; using flag", ENV_THREADS, raw)
        except ValueError:
            log.warning("invalid %s=%r; using flag", ENV_THREADS, raw)
    return max(1, int(flags.get_flag("process_thread_count")))

# per-worker inbox depth: small on purpose — the real buffering lives in the
# bounded process queues; the inbox only smooths the dispatch hop
INBOX_CAPACITY = 4

_SOURCE_TAG = b"__source__"


def shard_of(queue_key: int, source: Optional[bytes], n: int) -> int:
    """Affinity shard for a group: CRC32 over the source identity seeded
    with the process queue key.  Deterministic across processes (no Python
    hash randomisation) so replayed storms shard identically."""
    if n <= 1:
        return 0
    return zlib.crc32(source or b"", queue_key & 0xFFFFFFFF) % n


def group_source_id(group: PipelineEventGroup) -> Optional[bytes]:
    """The per-source ordering identity of a group: the ``__source__`` tag
    when an input sets one, else the originating file (path + inode — two
    rotated generations of one path may interleave but each stream keeps a
    stable home), else None.  Unkeyed groups of one pipeline all land on one
    worker — ordering-safe by construction."""
    src = group.get_tag(_SOURCE_TAG)
    if src is not None:
        return src.to_bytes()
    path = group.get_metadata(EventGroupMetaKey.LOG_FILE_PATH)
    if path is not None:
        inode = group.get_metadata(EventGroupMetaKey.LOG_FILE_INODE)
        pid = path.to_bytes()
        return (pid + b":" + inode.to_bytes()) if inode is not None else pid
    return None


class WorkerLane:
    """One worker's overlapped-dispatch ring (its device lane).

    loongstream: up to ``depth - 1`` groups' device work stays in flight
    per worker (``LOONG_STREAM_DEPTH``, default 3 ⇒ two pending groups
    while a third packs/dispatches).  The ring is strict FIFO — ``take()``
    removes and returns the OLDEST pending entry atomically, so the worker
    loop and the DevicePlane budget-relief hook can race to complete it
    and exactly one side wins, and completion (send) order always matches
    dispatch (pop) order: per-source ordering survives any depth.
    ``oldest_age()`` drives the auto-tuner's flush deadline — a pending
    group never rides the ring past it, bounding batch latency when the
    queue trickles."""

    __slots__ = ("worker_id", "depth", "capacity", "_lock", "_pending",
                 "_t0", "_held_since", "_held_s")

    def __init__(self, worker_id: int, depth: Optional[int] = None):
        from ..ops.device_stream import stream_depth
        self.worker_id = worker_id
        self.depth = depth if depth is not None else stream_depth()
        self.capacity = max(1, self.depth - 1)
        self._lock = threading.Lock()
        self._pending: deque = deque()   # [(pending, enqueued_at)]
        # loongprof: overlap accounting — how long this lane held a group
        # whose device work was in flight, over the lane's lifetime
        self._t0 = time.perf_counter()
        self._held_since = 0.0
        self._held_s = 0.0

    def put(self, pending) -> None:
        if pending is None:
            return
        now = time.perf_counter()
        with self._lock:
            assert len(self._pending) < self.capacity, "lane ring full"
            if not self._pending:
                self._held_since = now
            self._pending.append((pending, now))

    def take(self):
        """Remove and return the OLDEST pending entry (FIFO — the ring
        advance), or None."""
        with self._lock:
            if not self._pending:
                return None
            p, _t = self._pending.popleft()
            if not self._pending:
                self._held_s += time.perf_counter() - self._held_since
            return p

    def busy(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def full(self) -> bool:
        with self._lock:
            return len(self._pending) >= self.capacity

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def oldest_age(self) -> Optional[float]:
        """Seconds the oldest pending group has ridden the ring (None when
        empty) — compared against the auto-tuner's flush deadline."""
        with self._lock:
            if not self._pending:
                return None
            return time.perf_counter() - self._pending[0][1]

    def overlap_ratio(self) -> float:
        """Fraction of this lane's lifetime spent with device work in
        flight — near 0 means the worker never overlaps (host-bound or
        idle), near 1 means the lane is saturated (device-bound)."""
        now = time.perf_counter()
        with self._lock:
            held = self._held_s
            if self._pending:
                held += now - self._held_since
        elapsed = max(now - self._t0, 1e-9)
        return held / elapsed


class _ShardInbox:
    """Bounded SPSC handoff between the dispatch loop and one worker.
    A full inbox blocks the dispatcher (back-pressure); ``close()`` wakes
    the worker for final drain."""

    def __init__(self, capacity: int = INBOX_CAPACITY):
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._capacity = capacity
        self._closed = False

    def put(self, item, timeout: float = 1.0) -> bool:
        """Blocks while full.  Returns False only when closed (caller then
        owns the item again) or the wait timed out with no space."""
        deadline = time.monotonic() + timeout
        with self._not_full:
            while len(self._items) >= self._capacity and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._not_full.wait(remaining)
            if self._closed:
                return False
            self._items.append(item)
            self._not_empty.notify()
            return True

    def get(self, timeout: float = 0.2):
        with self._not_empty:
            if not self._items:
                if timeout > 0 and not self._closed:
                    self._not_empty.wait(timeout)
                if not self._items:
                    return None
            item = self._items.popleft()
            self._not_full.notify()
            return item

    def get_run(self, timeout: float = 0.2, max_groups: int = 8):
        """Backlog-aware drain (loongcolumn): pop the head item plus any
        consecutive items sharing its queue key, as one (key, groups) run —
        FIFO order preserved, so per-source ordering is untouched.  A
        trickle yields single-group runs; a backlog amortises the worker's
        per-dispatch hand-off."""
        with self._not_empty:
            if not self._items:
                if timeout > 0 and not self._closed:
                    self._not_empty.wait(timeout)
                if not self._items:
                    return None
            key, group = self._items.popleft()
            groups = [group]
            while self._items and len(groups) < max_groups \
                    and self._items[0][0] == key:
                groups.append(self._items.popleft()[1])
            self._not_full.notify_all()
            return key, groups

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def is_closed(self) -> bool:
        with self._lock:
            return self._closed

    def drained(self) -> bool:
        with self._lock:
            return self._closed and not self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class ProcessorRunner:
    def __init__(self, process_queue_manager: ProcessQueueManager,
                 pipeline_manager, thread_count: Optional[int] = None,
                 run_max_groups: Optional[int] = None):
        self.pqm = process_queue_manager
        self.pipeline_manager = pipeline_manager
        if thread_count is None:
            thread_count = resolve_thread_count()
        self.thread_count = max(1, int(thread_count))
        # loongcolumn backlog-aware pops: how many same-pipeline groups one
        # pop may hand a worker (1 = the pre-run per-group shape;
        # LOONG_POP_RUN overrides for experiments)
        if run_max_groups is None:
            try:
                run_max_groups = int(os.environ.get("LOONG_POP_RUN", "0")) \
                    or RUN_MAX_GROUPS
            except ValueError:
                run_max_groups = RUN_MAX_GROUPS
        self.run_max_groups = max(1, int(run_max_groups))
        self._threads: List[threading.Thread] = []
        self._dispatch_thread: Optional[threading.Thread] = None
        self._lanes: List[WorkerLane] = []
        self._inboxes: List[_ShardInbox] = []
        self._running = False
        # loongledger: groups popped from a queue/inbox but not yet
        # anchored in another occupancy counter (inbox / lane /
        # _in_process_cnt) — covers the hop so a descheduled worker
        # holding a group in a local variable cannot fake a quiesce.
        # Known residual sliver: the increment runs just AFTER the pop
        # returns (holding it across the blocking wait would count idle
        # workers as inflight and the auditor would never quiesce), so a
        # thread descheduled for 2+ audit intervals in the few
        # instructions between B_DEQUEUE and _note_in_hand(1) could still
        # slip the probe; the two-consecutive-quiesced-audits confirmation
        # is the backstop for that nanosecond window
        self._in_hand = 0
        self._in_hand_lock = threading.Lock()
        self.metrics = MetricsRecord(category="runner",
                                     labels={"runner": "processor"})
        self.in_groups = self.metrics.counter("in_event_groups_total")
        self.in_events = self.metrics.counter("in_events_total")
        self.in_bytes = self.metrics.counter("in_size_bytes")
        # active worker count: the exposition endpoint / self-monitor report
        # how many shards this agent actually runs (ISSUE 4 satellite)
        self.workers_gauge = self.metrics.gauge("process_workers")
        # pop → send-returned latency per group (process + device overlap +
        # downstream processors + route/flush enqueue); queue wait is its
        # own histogram on the process-queue side
        self.e2e_hist = self.metrics.histogram("pipeline_e2e_seconds")
        self.last_flush = time.monotonic()
        # every worker/dispatcher loop pumps the flush cadence: claiming
        # the interval must be atomic or two shards double-flush
        self._flush_claim = threading.Lock()

    # -- producer API -------------------------------------------------------

    def push_queue(self, key: int, group: PipelineEventGroup,
                   retry_times: int = 10) -> bool:
        for _ in range(retry_times):
            if self.pqm.push_queue(key, group):
                return True
            time.sleep(0.01)
        AlarmManager.instance().send_alarm(
            AlarmType.PROCESS_QUEUE_FULL,
            f"push rejected after {retry_times} retries (queue {key})",
            AlarmLevel.WARNING)
        return False

    # -- lifecycle ----------------------------------------------------------

    def init(self) -> None:
        global _active_runner
        self._running = True
        self._lanes = [WorkerLane(i) for i in range(self.thread_count)]
        self.workers_gauge.set(self.thread_count)
        _active_runner = self
        if self.thread_count == 1:
            t = threading.Thread(target=self._run_single, args=(0,),
                                 name="processor-0", daemon=True)
            t.start()
            self._threads.append(t)
            return
        self._inboxes = [_ShardInbox() for _ in range(self.thread_count)]
        for i in range(self.thread_count):
            t = threading.Thread(target=self._run_worker, args=(i,),
                                 name=f"processor-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        self._dispatch_thread = threading.Thread(
            target=self._run_dispatch, name="processor-dispatch", daemon=True)
        self._dispatch_thread.start()

    def inbox_depths(self) -> List[int]:
        """Queued groups per worker inbox (empty list when single-worker:
        the reference shape has no dispatch hop to observe)."""
        return [len(ib) for ib in self._inboxes]

    def _note_in_hand(self, delta: int) -> None:
        # clamped at zero: the ledger can come on mid-run, making the
        # first decrement unpaired — never let that offset real occupancy
        with self._in_hand_lock:
            self._in_hand = max(0, self._in_hand + delta)

    def in_hand_count(self) -> int:
        """Groups currently between a queue/inbox pop and their next
        counted station — the ledger's live-occupancy probe."""
        with self._in_hand_lock:
            return self._in_hand

    def lane_overlap(self) -> List[float]:
        """Per-lane device-overlap ratio (loongprof utilization): the
        fraction of each worker's lifetime its lane held in-flight device
        work.  Uniformly low with a growing
        ``device_idle_while_backlogged_ms`` counter says "shard more";
        uniformly high says the device is the bottleneck."""
        return [lane.overlap_ratio() for lane in self._lanes]

    def stop(self) -> None:
        global _active_runner
        if _active_runner is self:
            _active_runner = None
        self._running = False
        self.pqm.wake_up()
        if self._dispatch_thread is not None:
            # the dispatch loop drains the process queues into the inboxes
            # and closes them; workers exit after their final drain
            self._dispatch_thread.join(timeout=10)
            if self._dispatch_thread.is_alive():
                # wedged dispatch must not wedge stop(): close inboxes so
                # workers can still finish what they already hold
                for ib in self._inboxes:
                    ib.close()
            self._dispatch_thread = None
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()
        # inboxes/lanes stay allocated (closed): a dispatch thread that
        # out-lived its join timeout may still call _route — an empty list
        # there would IndexError it mid-drain; init() rebuilds both
        # a stopped runner exports nothing further; its record must not
        # accumulate in WriteMetrics across restarts (loonglint
        # metric-naming ownership rule)
        self.metrics.mark_deleted()

    # -- shard routing ------------------------------------------------------

    def _shard(self, key: int, group: PipelineEventGroup) -> int:
        return shard_of(key, group_source_id(group), self.thread_count)

    def _pump_timeout_flush(self) -> None:
        now = time.monotonic()
        with self._flush_claim:
            claimed = now - self.last_flush >= BATCH_FLUSH_INTERVAL_S
            if claimed:
                self.last_flush = now
        # flush outside the claim: only the interval arithmetic needs
        # atomicity, the hooks below take their own locks
        if claimed:
            try:
                TimeoutFlushManager.instance().flush_timeout_batches()
            except Exception:  # noqa: BLE001 — a bad hook must not kill
                # the thread pumping all timeout flushing agent-wide
                log.exception("timeout flush failed")
            try:
                # loongstream: the width auto-tuner re-reads the device
                # utilization accounting on the same 1 s cadence and moves
                # the lane-ring flush deadline (observe-only, fail-soft)
                auto_tuner().maybe_adjust()
            except Exception:  # noqa: BLE001
                log.exception("stream tuner adjust failed")

    def _run_dispatch(self) -> None:
        """Sharded mode only: pop the queue manager, route by affinity.
        Also pumps timeout flushes (the reference's thread-0 duty).
        Pops are backlog-aware runs (loongcolumn): one lock cycle hands
        the dispatcher up to RUN_MAX_GROUPS same-pipeline groups, each
        still routed to its affinity shard individually."""
        while self._running:
            self._pump_timeout_flush()
            run = self.pqm.pop_run(timeout=0.2,
                                   max_groups=self.run_max_groups)
            if run is None:
                continue
            self._handle_routed_run(run)
        # drain remaining items on stop: keep affinity so ordering holds
        # through shutdown too
        while True:
            run = self.pqm.pop_run(timeout=0,
                                   max_groups=self.run_max_groups)
            if run is None:
                break
            self._handle_routed_run(run)
        for ib in self._inboxes:
            ib.close()

    def _handle_routed_run(self,
                           run: Tuple[int, List[PipelineEventGroup]]) -> None:
        """Route one popped run while the in-hand counter covers the gap
        until each group lands in an inbox (or finishes inline)."""
        key, groups = run
        if not ledger.is_on():
            for group in groups:
                self._route((key, group))
            return
        self._note_in_hand(len(groups))
        left = len(groups)
        try:
            for group in groups:
                self._route((key, group))
                self._note_in_hand(-1)
                left -= 1
        finally:
            if left:        # a raising route must not leave phantom in-hand
                self._note_in_hand(-left)

    def _route(self, item: Tuple[int, PipelineEventGroup]) -> None:
        key, group = item
        shard = self._shard(key, group)
        inbox = self._inboxes[shard]
        stalled = False
        # a full inbox blocks here — that is the back-pressure hop; the
        # timeout only exists so a wedged worker cannot wedge dispatch
        # (and with it the flush pump) forever.  Known tradeoff: while one
        # shard's inbox is full, dispatch (and with it every pipeline)
        # waits — the same agent-wide escalation the reference's
        # thread_count=1 default has, traded here for per-source ordering;
        # per-pipeline dispatch isolation is future work
        # (docs/performance.md)
        while not inbox.put(item, timeout=1.0):
            if inbox.is_closed():
                # forced shutdown (stop() closed the inboxes after the
                # drain-join timed out): process inline on this thread
                # rather than dropping — the old single-thread drain
                # semantics; ordering past this point is best-effort
                self._process_one(key, group)
                return
            if not stalled:
                # a worker whose full inbox blocked dispatch for a whole
                # timeout round is stalled — one flight event per episode
                # (no lock held here: the put timed out and returned)
                stalled = True
                flight.record("worker.stall", worker=shard,
                              depth=len(inbox))
            self._pump_timeout_flush()

    # -- workers ------------------------------------------------------------

    def _make_relief(self, lane: WorkerLane):
        """Budget-relief hook bound to ONE lane: when this worker waits for
        in-flight budget while dispatching, finish the overlapped group the
        lane holds so the bytes it owns are released.  Bound explicitly (not
        read from TLS at call time) so the hook always completes the owning
        worker's group even if relief plumbing ever runs off-thread."""
        def _relieve() -> bool:
            p = lane.take()
            if p is None:
                return False
            self._complete(p)
            return True
        return _relieve

    def _advance_ring(self, lane: WorkerLane) -> None:
        """loongstream ring discipline, shared by both loops: complete the
        OLDEST pending group when the ring is at capacity (the span-return
        stage of the pipeline: group N-depth+1 materialises while the
        device computes the newer ones) or when it outlived the
        auto-tuner's flush deadline (latency backstop for trickle
        traffic)."""
        while lane.full():
            self._complete_oldest(lane)
        age = lane.oldest_age()
        if age is not None and age > auto_tuner().flush_deadline_s():
            self._complete_oldest(lane)

    def _run_single(self, worker_id: int) -> None:
        """thread_count == 1: the reference shape — pop the queue manager
        directly, no dispatch hop.  Pops are backlog-aware runs
        (loongcolumn): the per-pop/per-dispatch hand-off amortises over
        whatever occupancy the queue actually holds."""
        lane = self._lanes[worker_id]
        set_budget_relief(self._make_relief(lane))
        prof.push_marker("worker", f"processor-{worker_id}")
        had_item = False
        try:
            while self._running:
                self._pump_timeout_flush()
                # while device work is in flight, poll rather than sleep: an
                # empty queue means the overlap window closes and we complete
                run = self.pqm.pop_run(
                    timeout=0.0 if lane.busy() else 0.2,
                    max_groups=self.run_max_groups)
                if run is None:
                    had_item = False
                    self._complete_oldest(lane)
                    continue
                if had_item or len(run[1]) > 1:
                    # sustained backlog on the single worker (consecutive
                    # non-empty pops, or a multi-group run): probe the
                    # device-idle accounting (the sharded loop probes on
                    # inbox depth instead)
                    note_host_backlog()
                had_item = True
                self._handle_run(run[0], run[1], lane)
            self._complete_lane(lane)
            # drain remaining items on stop
            while True:
                run = self.pqm.pop_run(timeout=0,
                                   max_groups=self.run_max_groups)
                if run is None:
                    break
                self._handle_run(run[0], run[1], None)
        finally:
            prof.pop_marker()
            set_budget_relief(None)

    def _chip_lane_for(self, worker_id: int):
        """loongmesh: this worker's home chip lane (source → worker →
        chip: the CRC32 affinity hash picked the worker, ``worker_id %
        n_chips`` picks the chip).  None when ≤1 device is attached or
        lane routing is off (``LOONG_MESH_LANES=0``) — dispatches then
        stay on the full-mesh / single-device path.  Fail-soft: a missing
        backend must never kill a worker thread."""
        try:
            return chip_lanes.router().lane_for_worker(worker_id)
        except Exception:  # noqa: BLE001
            log.exception("chip-lane routing unavailable; worker %d "
                          "stays unbound", worker_id)
            return None

    def chip_lane_map(self) -> List[Optional[int]]:
        """worker index -> bound chip index (None = unbound), for
        /debug/status and the affinity-determinism tests."""
        out: List[Optional[int]] = []
        for i in range(self.thread_count if self.thread_count > 1 else 0):
            lane = self._chip_lane_for(i)
            out.append(lane.index if lane is not None else None)
        return out

    def _run_worker(self, worker_id: int) -> None:
        """Sharded mode: consume this worker's inbox with the same
        overlapped device lane ring as the single-thread loop.  The
        worker binds to its home chip lane for the duration — every
        device dispatch it makes lands on that chip."""
        lane = self._lanes[worker_id]
        inbox = self._inboxes[worker_id]
        set_budget_relief(self._make_relief(lane))
        chip_lanes.set_thread_lane(self._chip_lane_for(worker_id))
        prof.push_marker("worker", f"processor-{worker_id}")
        try:
            while True:
                run = inbox.get_run(
                    timeout=0.0 if lane.busy() else 0.2,
                    max_groups=self.run_max_groups)
                if run is None:
                    self._complete_oldest(lane)
                    if inbox.drained():
                        break
                    continue
                if len(inbox):
                    # host has backlog at this very moment: charge any
                    # device-idle gap (utilization accounting — the
                    # "shard more vs device-bound" counter)
                    note_host_backlog()
                self._handle_run(run[0], run[1], lane)
            self._complete_lane(lane)
        finally:
            prof.pop_marker()
            chip_lanes.set_thread_lane(None)
            set_budget_relief(None)

    def _handle_run(self, key: int, groups: List[PipelineEventGroup],
                    lane: Optional[WorkerLane]) -> None:
        """One popped run through dispatch → ring advance → lane, with
        the in-hand counter covering the whole hop (a group anchored in
        the lane ring or _in_process_cnt is visible to live_inflight;
        this covers the slivers in between).

        Dispatch is PER GROUP even though the pop was a run:
         * the lane ring + budget-relief protocol is per pending entry —
           a whole run inside ONE process_begin would let group N+1's
           device dispatch wait on budget held by group N's pending,
           which only materialises after the run returns (intra-run
           budget deadlock the relief hook cannot see);
         * sampled tracing draws one deterministic key per group
           ("pipeline:N") — a replayed storm must trace the identical
           population.
        The run amortises the HAND-OFF (one queue lock/CV cycle, one
        aggregated dequeue record, one inbox drain per run) — that, not
        chain batching, was the measured cost."""
        led = ledger.is_on()
        if led:
            self._note_in_hand(len(groups))
        try:
            for group in groups:
                if lane is None:
                    self._process_one(key, group)
                    continue
                nxt = self._dispatch_one(key, group, lane=lane)
                # dispatch-before-advance is the overlap: the device now
                # holds group N+1 while we materialise + send the oldest
                # ring entry (N-depth+1)
                self._advance_ring(lane)
                lane.put(nxt)
        finally:
            if led:
                self._note_in_hand(-len(groups))

    def _dispatch_one(self, key: int, group: PipelineEventGroup,
                      lane: Optional[WorkerLane] = None):
        """Host pre-processing + device dispatch for one group.  Returns
        a pending handle when device work stays in flight, else None
        (group fully processed and sent).

        Ordering invariant: when this group resolves on the host tier
        (finish is None) it is SENT here, inline — so the worker's lane
        must be completed first.  Otherwise a device-routed group N could
        still sit in the lane while host-routed group N+1 of the SAME
        source overtakes it at the sink (observed in the agent drive: the
        first group of a stream pays the XLA compile on the device path
        while later small groups take the native walker)."""
        pipeline = self.pipeline_manager.find_pipeline_by_queue_key(key)
        n_events = len(group)
        if pipeline is None:
            log.warning("no pipeline for queue key %d; dropping group", key)
            ack_watermark.ack_groups([group], force=True)
            if ledger.is_on() or slo.is_on():
                q = self.pqm.get_queue(key)
                # hot reload can delete the queue between pop and here:
                # attribute the drop via the manager's tombstone so the
                # ingesting pipeline's books still balance
                name = (q.pipeline_name if q is not None
                        else self.pqm.retired_pipeline_name(key))
                if ledger.is_on():
                    ledger.record(name, ledger.B_DROP, n_events,
                                  group.data_size(), tag="no_pipeline")
                if slo.is_on():
                    slo.observe_groups(name, [group], slo.OUTCOME_DROP)
            return None
        self.in_groups.add(1)
        self.in_events.add(n_events)
        self.in_bytes.add(group.data_size())
        groups = [group]
        t0 = time.perf_counter()
        sp = None
        tracer = trace.active_tracer()
        if tracer is not None:
            # deterministic per-group sampling: the Nth group of pipeline P
            # draws from (seed, "P:N") only — a replayed soak traces the
            # identical group set (docs/observability.md)
            gkey = tracer.next_group_key(pipeline.name or "pipeline")
            if tracer.should_sample(gkey):
                sp = tracer.start_span(
                    "pipeline.process", trace_id=gkey,
                    attrs={"pipeline": pipeline.name, "events": n_events})
                tracer.push_current(sp)
        prof.push_marker("pipeline", pipeline.name or "pipeline")
        # loongtenant: device dispatches made inside this chain walk count
        # against THIS pipeline's budget share (ops/device_plane).
        # Save/restore, not set/clear: the budget-relief hook completes a
        # lane group INSIDE another pipeline's submit wait on this same
        # thread — clearing would strip the outer dispatch's binding
        prev_tenant = current_tenant()
        set_thread_tenant(pipeline.name or None)
        try:
            try:
                finish = pipeline.process_begin(groups)
            except Exception:  # noqa: BLE001
                log.exception("pipeline %s processing failed", pipeline.name)
                self._ledger_error_drop(pipeline, groups)
                self._finish_group(sp, t0, "error")
                return None
            if finish is None:
                if lane is not None:
                    # drain the overlapped group BEFORE this inline send:
                    # same worker ⇒ possibly same source; send order = pop
                    # order
                    self._complete_lane(lane)
                self._send(pipeline, groups)
                self._finish_group(sp, t0, "ok")
                return None
        finally:
            set_thread_tenant(prev_tenant)
            prof.pop_marker()
        # the group's device work stays in flight: detach its span from
        # this thread so the NEXT group's dispatch does not nest under it
        if sp is not None:
            tracer.pop_current(sp)
        lane_tag = (f"lane{lane.worker_id}" if lane is not None else "inline")
        if ledger.is_on():
            ledger.record(pipeline.name, ledger.B_DEVICE_SUBMIT,
                          sum(len(g) for g in groups), tag=lane_tag)
        return pipeline, groups, finish, sp, t0, lane_tag

    def _ledger_error_drop(self, pipeline, groups) -> None:
        """A processing exception terminally discards the group's events:
        without this record the conservation residual would read the bug
        as a silent loss instead of an attributed drop."""
        ack_watermark.ack_groups(groups, force=True)
        if slo.is_on():
            slo.observe_groups(pipeline.name, groups, slo.OUTCOME_DROP)
        ledger.record(pipeline.name, ledger.B_DROP,
                      sum(len(g) for g in groups), tag="process_error")

    def _finish_group(self, sp, t0: float, status: str) -> None:
        self.e2e_hist.observe(time.perf_counter() - t0)
        if sp is not None:
            tracer = trace.active_tracer()
            if tracer is not None:
                tracer.pop_current(sp)
            sp.end(status)

    def _complete_oldest(self, lane: WorkerLane) -> None:
        """Advance the lane ring one step: materialise + send its oldest
        pending group (no-op when empty)."""
        p = lane.take()
        if p is not None:
            self._complete(p)

    def _complete_lane(self, lane: WorkerLane) -> None:
        """Drain the WHOLE lane ring in FIFO order — required before any
        inline (host-tier) send of a possibly-same-source group, and on
        worker exit."""
        while True:
            p = lane.take()
            if p is None:
                return
            self._complete(p)

    def _complete(self, pending) -> None:
        pipeline, groups, finish, sp, t0, lane_tag = pending
        tracer = trace.active_tracer()
        if sp is not None and tracer is not None:
            # re-attach: device materialisation + downstream processors +
            # send events belong to this group's span
            tracer.push_current(sp)
        prof.push_marker("pipeline", pipeline.name or "pipeline")
        # in-hand across the whole completion: the lane entry was already
        # take()n and finish()'s exit drops _in_process_cnt BEFORE the
        # send — without this, a sink write stalling mid-_send (NFS,
        # loaded CI) leaves the group in no occupancy counter and a
        # stable ledger, faking a quiesce into a false residual alarm
        led = ledger.is_on()
        if led:
            self._note_in_hand(1)
        # completion may re-dispatch (fused demotion re-runs, drain hops):
        # those submits bill this pipeline's tenant share too.  _complete
        # runs from the budget-relief hook inside ANOTHER pipeline's
        # submit wait, so restore rather than clear
        prev_tenant = current_tenant()
        set_thread_tenant(pipeline.name or None)
        try:
            try:
                finish()
            except Exception:  # noqa: BLE001
                log.exception("pipeline %s processing failed", pipeline.name)
                self._ledger_error_drop(pipeline, groups)
                self._finish_group(sp, t0, "error")
                return
            if ledger.is_on():
                # device work resolved: the group's spans are host-resident
                # again — the submit→materialize gap is the ring occupancy
                ledger.record(pipeline.name, ledger.B_DEVICE_MATERIALIZE,
                              sum(len(g) for g in groups), tag=lane_tag)
            self._send(pipeline, groups)
            self._finish_group(sp, t0, "ok")
        finally:
            if led:
                self._note_in_hand(-1)
            set_thread_tenant(prev_tenant)
            prof.pop_marker()

    def _send(self, pipeline, groups) -> None:
        try:
            pipeline.send(groups)
        except Exception:  # noqa: BLE001
            log.exception("pipeline %s send failed", pipeline.name)
            # best-effort terminal record: send() may have routed part of
            # the batch before raising, so a nonzero (negative) residual
            # here is the auditor doing its job on a genuine bug path
            ledger.record(pipeline.name, ledger.B_DROP,
                          sum(len(g) for g in groups), tag="send_error")

    def _process_one(self, key: int, group: PipelineEventGroup) -> None:
        pending = self._dispatch_one(key, group)
        if pending is not None:
            self._complete(pending)
