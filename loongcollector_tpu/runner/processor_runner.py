"""ProcessorRunner: the processing thread engine.

Reference: core/runner/ProcessorRunner.cpp — N worker threads (default 1,
app_config/AppConfig.cpp:58) pop from the process-queue manager (priority RR),
find the owning pipeline, run Process then Send (:90-189); thread 0 also
pumps batch timeout flushes (:109-112); producer API PushQueue with bounded
retries (:72-88).

TPU note: one runner thread per device keeps the device queue full while
host pre/post-processing of the NEXT batch overlaps with device execution
(the jax dispatch is async until results are read).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..models import PipelineEventGroup
from ..monitor.metrics import MetricsRecord
from ..pipeline.batch.timeout_flush_manager import TimeoutFlushManager
from ..pipeline.queue.process_queue_manager import ProcessQueueManager
from ..utils.logger import get_logger

log = get_logger("processor_runner")

BATCH_FLUSH_INTERVAL_S = 1.0


class ProcessorRunner:
    def __init__(self, process_queue_manager: ProcessQueueManager,
                 pipeline_manager, thread_count: int = 1):
        self.pqm = process_queue_manager
        self.pipeline_manager = pipeline_manager
        self.thread_count = thread_count
        self._threads: List[threading.Thread] = []
        self._running = False
        self.metrics = MetricsRecord(category="runner",
                                     labels={"runner": "processor"})
        self.in_groups = self.metrics.counter("in_event_groups_total")
        self.in_events = self.metrics.counter("in_events_total")
        self.in_bytes = self.metrics.counter("in_size_bytes")
        self.last_flush = time.monotonic()

    # -- producer API -------------------------------------------------------

    def push_queue(self, key: int, group: PipelineEventGroup,
                   retry_times: int = 10) -> bool:
        for _ in range(retry_times):
            if self.pqm.push_queue(key, group):
                return True
            time.sleep(0.01)
        return False

    # -- lifecycle ----------------------------------------------------------

    def init(self) -> None:
        self._running = True
        for i in range(self.thread_count):
            t = threading.Thread(target=self._run, args=(i,),
                                 name=f"processor-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        self.pqm.wake_up()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    # -- worker -------------------------------------------------------------

    def _run(self, thread_no: int) -> None:
        while self._running:
            if thread_no == 0:
                now = time.monotonic()
                if now - self.last_flush >= BATCH_FLUSH_INTERVAL_S:
                    self.last_flush = now
                    try:
                        TimeoutFlushManager.instance().flush_timeout_batches()
                    except Exception:  # noqa: BLE001 — a bad hook must not
                        # kill thread 0 (all timeout flushing agent-wide)
                        log.exception("timeout flush failed")
            item = self.pqm.pop_item(timeout=0.2)
            if item is None:
                continue
            key, group = item
            self._process_one(key, group)
        # drain remaining items on stop
        while True:
            item = self.pqm.pop_item(timeout=0)
            if item is None:
                break
            self._process_one(*item)

    def _process_one(self, key: int, group: PipelineEventGroup) -> None:
        pipeline = self.pipeline_manager.find_pipeline_by_queue_key(key)
        if pipeline is None:
            log.warning("no pipeline for queue key %d; dropping group", key)
            return
        self.in_groups.add(1)
        self.in_events.add(len(group))
        self.in_bytes.add(group.data_size())
        groups = [group]
        try:
            pipeline.process(groups)
            pipeline.send(groups)
        except Exception:  # noqa: BLE001
            log.exception("pipeline %s processing failed", pipeline.name)
