"""ProcessorRunner: the processing thread engine.

Reference: core/runner/ProcessorRunner.cpp — N worker threads (default 1,
app_config/AppConfig.cpp:58) pop from the process-queue manager (priority RR),
find the owning pipeline, run Process then Send (:90-189); thread 0 also
pumps batch timeout flushes (:109-112); producer API PushQueue with bounded
retries (:72-88).

TPU note — the async device data plane (SURVEY §7 step 4): each worker keeps
ONE group's device work in flight.  The loop dispatches group N+1 (host
pre-processing + pack + async kernel dispatch via Pipeline.process_begin)
BEFORE materialising group N, so the device executes N while the host packs
N+1 and then runs N's downstream processors + send.  Device back-pressure is
the DevicePlane in-flight byte budget: when the device stalls, dispatch
blocks, this thread stops popping, and the bounded process queues fill to
their high watermark, feedback-blocking the inputs
(core/collection_pipeline/queue/BoundedProcessQueue.cpp:89-93 contract,
extended one hop onto the device).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from .. import trace
from ..models import PipelineEventGroup
from ..monitor.alarms import AlarmLevel, AlarmManager, AlarmType
from ..monitor.metrics import MetricsRecord
from ..ops.device_plane import set_budget_relief
from ..pipeline.batch.timeout_flush_manager import TimeoutFlushManager
from ..pipeline.queue.process_queue_manager import ProcessQueueManager
from ..utils.logger import get_logger

log = get_logger("processor_runner")

BATCH_FLUSH_INTERVAL_S = 1.0


class ProcessorRunner:
    def __init__(self, process_queue_manager: ProcessQueueManager,
                 pipeline_manager, thread_count: int = 1):
        self.pqm = process_queue_manager
        self.pipeline_manager = pipeline_manager
        self.thread_count = thread_count
        self._tls = threading.local()
        self._threads: List[threading.Thread] = []
        self._running = False
        self.metrics = MetricsRecord(category="runner",
                                     labels={"runner": "processor"})
        self.in_groups = self.metrics.counter("in_event_groups_total")
        self.in_events = self.metrics.counter("in_events_total")
        self.in_bytes = self.metrics.counter("in_size_bytes")
        # pop → send-returned latency per group (process + device overlap +
        # downstream processors + route/flush enqueue); queue wait is its
        # own histogram on the process-queue side
        self.e2e_hist = self.metrics.histogram("pipeline_e2e_seconds")
        self.last_flush = time.monotonic()

    # -- producer API -------------------------------------------------------

    def push_queue(self, key: int, group: PipelineEventGroup,
                   retry_times: int = 10) -> bool:
        for _ in range(retry_times):
            if self.pqm.push_queue(key, group):
                return True
            time.sleep(0.01)
        AlarmManager.instance().send_alarm(
            AlarmType.PROCESS_QUEUE_FULL,
            f"push rejected after {retry_times} retries (queue {key})",
            AlarmLevel.WARNING)
        return False

    # -- lifecycle ----------------------------------------------------------

    def init(self) -> None:
        self._running = True
        for i in range(self.thread_count):
            t = threading.Thread(target=self._run, args=(i,),
                                 name=f"processor-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        self.pqm.wake_up()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()
        # a stopped runner exports nothing further; its record must not
        # accumulate in WriteMetrics across restarts (loonglint
        # metric-naming ownership rule)
        self.metrics.mark_deleted()

    # -- worker -------------------------------------------------------------

    def _run(self, thread_no: int) -> None:
        # one group's device work stays in flight per worker; kept in TLS so
        # the DevicePlane budget-relief hook can complete it if this thread
        # ever blocks dispatching the next group (no-deadlock invariant)
        self._tls.pending = None
        set_budget_relief(self._relieve_budget)
        while self._running:
            if thread_no == 0:
                now = time.monotonic()
                if now - self.last_flush >= BATCH_FLUSH_INTERVAL_S:
                    self.last_flush = now
                    try:
                        TimeoutFlushManager.instance().flush_timeout_batches()
                    except Exception:  # noqa: BLE001 — a bad hook must not
                        # kill thread 0 (all timeout flushing agent-wide)
                        log.exception("timeout flush failed")
            # while device work is in flight, poll rather than sleep: an
            # empty queue means the overlap window closes and we complete
            item = self.pqm.pop_item(
                timeout=0.0 if self._tls.pending is not None else 0.2)
            if item is None:
                self._complete_pending()
                continue
            nxt = self._dispatch_one(*item)
            # dispatch-before-complete is the overlap: the device now holds
            # group N+1 while we materialise + send group N on the host
            self._complete_pending()
            self._tls.pending = nxt
        self._complete_pending()
        # drain remaining items on stop
        while True:
            item = self.pqm.pop_item(timeout=0)
            if item is None:
                break
            self._process_one(*item)

    def _dispatch_one(self, key: int, group: PipelineEventGroup):
        """Host pre-processing + device dispatch for one group.  Returns a
        pending handle when device work stays in flight, else None (group
        fully processed and sent)."""
        pipeline = self.pipeline_manager.find_pipeline_by_queue_key(key)
        if pipeline is None:
            log.warning("no pipeline for queue key %d; dropping group", key)
            return None
        self.in_groups.add(1)
        self.in_events.add(len(group))
        self.in_bytes.add(group.data_size())
        t0 = time.perf_counter()
        sp = None
        tracer = trace.active_tracer()
        if tracer is not None:
            # deterministic per-group sampling: the Nth group of pipeline P
            # draws from (seed, "P:N") only — a replayed soak traces the
            # identical group set (docs/observability.md)
            gkey = tracer.next_group_key(pipeline.name or "pipeline")
            if tracer.should_sample(gkey):
                sp = tracer.start_span(
                    "pipeline.process", trace_id=gkey,
                    attrs={"pipeline": pipeline.name, "events": len(group)})
                tracer.push_current(sp)
        groups = [group]
        try:
            finish = pipeline.process_begin(groups)
        except Exception:  # noqa: BLE001
            log.exception("pipeline %s processing failed", pipeline.name)
            self._finish_group(sp, t0, "error")
            return None
        if finish is None:
            self._send(pipeline, groups)
            self._finish_group(sp, t0, "ok")
            return None
        # the group's device work stays in flight: detach its span from
        # this thread so the NEXT group's dispatch does not nest under it
        if sp is not None:
            tracer.pop_current(sp)
        return pipeline, groups, finish, sp, t0

    def _finish_group(self, sp, t0: float, status: str) -> None:
        self.e2e_hist.observe(time.perf_counter() - t0)
        if sp is not None:
            tracer = trace.active_tracer()
            if tracer is not None:
                tracer.pop_current(sp)
            sp.end(status)

    def _complete_pending(self) -> None:
        p = getattr(self._tls, "pending", None)
        if p is not None:
            self._tls.pending = None
            self._complete(p)

    def _relieve_budget(self) -> bool:
        """DevicePlane relief hook: when this thread waits for in-flight
        budget while dispatching, finish the overlapped group it holds so
        the bytes it owns are released."""
        p = getattr(self._tls, "pending", None)
        if p is None:
            return False
        self._tls.pending = None
        self._complete(p)
        return True

    def _complete(self, pending) -> None:
        pipeline, groups, finish, sp, t0 = pending
        tracer = trace.active_tracer()
        if sp is not None and tracer is not None:
            # re-attach: device materialisation + downstream processors +
            # send events belong to this group's span
            tracer.push_current(sp)
        try:
            finish()
        except Exception:  # noqa: BLE001
            log.exception("pipeline %s processing failed", pipeline.name)
            self._finish_group(sp, t0, "error")
            return
        self._send(pipeline, groups)
        self._finish_group(sp, t0, "ok")

    def _send(self, pipeline, groups) -> None:
        try:
            pipeline.send(groups)
        except Exception:  # noqa: BLE001
            log.exception("pipeline %s send failed", pipeline.name)

    def _process_one(self, key: int, group: PipelineEventGroup) -> None:
        pending = self._dispatch_one(key, group)
        if pending is not None:
            self._complete(pending)
