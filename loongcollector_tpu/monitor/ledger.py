"""loongledger: end-to-end event-conservation accounting.

The zero-loss guarantee the chaos storms assert post-hoc (ISSUE 2) becomes
an always-on observability plane: every hand-off on the event path records
into a per-(pipeline, boundary) ledger of event/byte totals, so the
conservation residual

    residual = (ingest + process_expand + fanout + replay)
             - (send_ok + process_drop + spill + quarantine + drop)
             - inflight

is computable at any instant from one snapshot.  At a QUIESCED instant —
two identical consecutive snapshots and zero observed live occupancy —
``inflight`` is zero and a nonzero residual means an event crossed into
the agent and vanished without a ledger entry: a silent loss (or a code
path that discards without ``ledger.record`` — loonglint's
``unledgered-drop`` checker is the static side of the same contract).

Boundary catalogue (docs/observability.md#event-conservation-ledger):

  ingest               input read (file reader, test/bench harnesses)
  enqueue / dequeue    watermark process queues (enqueue at queue admit,
                       dequeue at queue pop); the dequeue→process_in gap
                       covers the dispatch hop + per-worker inboxes,
                       whose occupancy live_inflight() observes directly
  process_in           events entering the processor chain
  process_expand       events CREATED mid-chain (split 1 raw -> N lines;
                       also drain re-entry of held multiline carries)
  process_drop         events retired mid-chain, attributed to the
                       dropping plugin (includes events HELD across
                       groups by stateful processors — the matching
                       release records process_expand tag="drain")
  process_out          events leaving the chain toward the flushers
  device_submit /      group enters / leaves a worker lane's overlapped
  device_materialize   device ring (loongstream), tagged per lane
  serialize            events serialized into a sink payload
  send_ok / send_fail  terminal delivery / one failed attempt (partial-ack
                       aware: a Kafka ack-window cut ledgers the acked
                       prefix as send_ok, the unacked tail as send_fail
                       and retries it — never double-counted)
  spill / replay /     disk buffer traffic (breaker spill-on-open, exit
  quarantine           drain, corrupt-at-rest quarantine)
  fanout               extra copies minted when the router matches more
                       than one flusher
  drop                 explicit terminal discard, reason-tagged
  agg_in / agg_fold /  the loongagg windowed rollup contraction: rows in,
  agg_emit             rows consumed by the fold (sink), rollup rows
                       minted at window close (source); open windows are
                       live occupancy via the aggregator's
                       open_window_rows probe

Chaos-plane idiom: the ledger is OFF by default and every hook is one
module-global read (``ledger.is_on()``) + branch — gated at <=5% by
scripts/ledger_overhead.py in lint.sh.  ``LOONG_LEDGER=1`` turns the
accounting on; ``LOONG_LEDGER_AUDIT=1`` additionally runs the
ConservationAuditor continuously, raising ``CONSERVATION_RESIDUAL_ALARM``
plus a flight-recorder entry whenever a quiesced snapshot shows a nonzero
residual.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_LEDGER = "LOONG_LEDGER"
ENV_AUDIT = "LOONG_LEDGER_AUDIT"
ENV_AUDIT_INTERVAL = "LOONG_LEDGER_AUDIT_INTERVAL"

# -- boundary names ---------------------------------------------------------

B_INGEST = "ingest"
B_ENQUEUE = "enqueue"
B_DEQUEUE = "dequeue"
B_PROCESS_IN = "process_in"
B_PROCESS_OUT = "process_out"
B_PROCESS_DROP = "process_drop"
B_PROCESS_EXPAND = "process_expand"
B_DEVICE_SUBMIT = "device_submit"
B_DEVICE_MATERIALIZE = "device_materialize"
B_SERIALIZE = "serialize"
B_SEND_OK = "send_ok"
B_SEND_FAIL = "send_fail"
B_SPILL = "spill"
B_REPLAY = "replay"
B_QUARANTINE = "quarantine"
B_FANOUT = "fanout"
B_DROP = "drop"
# loongagg: the windowed fold is an N→M contraction with its own counted,
# attributed boundaries — agg_in (rows entering the rollup aggregator,
# informational like process_in), agg_fold (rows CONSUMED by the fold: a
# residual sink — the events are accounted for, their content now lives
# in open-window partials the auditor counts as live occupancy), agg_emit
# (rollup rows MINTED at window close: a residual source that then flows
# to the normal serialize/send_ok exits)
B_AGG_IN = "agg_in"
B_AGG_FOLD = "agg_fold"
B_AGG_EMIT = "agg_emit"

BOUNDARIES = (B_INGEST, B_ENQUEUE, B_DEQUEUE, B_PROCESS_IN, B_PROCESS_OUT,
              B_PROCESS_DROP, B_PROCESS_EXPAND, B_DEVICE_SUBMIT,
              B_DEVICE_MATERIALIZE, B_SERIALIZE, B_SEND_OK, B_SEND_FAIL,
              B_SPILL, B_REPLAY, B_QUARANTINE, B_FANOUT, B_DROP,
              B_AGG_IN, B_AGG_FOLD, B_AGG_EMIT)

#: residual = sum(sources) - sum(sinks) - inflight
SOURCE_BOUNDARIES = (B_INGEST, B_PROCESS_EXPAND, B_FANOUT, B_REPLAY,
                     B_AGG_EMIT)
SINK_BOUNDARIES = (B_SEND_OK, B_PROCESS_DROP, B_SPILL, B_QUARANTINE, B_DROP,
                   B_AGG_FOLD)


class EventLedger:
    """Per-(pipeline, boundary[, tag]) event/byte totals.

    One short lock around two integer adds per record() — the counters are
    process-lifetime absolutes (never drained), so a snapshot is directly
    comparable across time and the residual needs no delta bookkeeping."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (pipeline, boundary, tag) -> [events, bytes]
        self._cells: Dict[Tuple[str, str, str], List[int]] = {}

    def record(self, pipeline: str, boundary: str, events: int,
               nbytes: int = 0, tag: str = "") -> None:
        key = (pipeline or "", boundary, tag)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = [0, 0]
            cell[0] += events
            cell[1] += nbytes

    def total(self, pipeline: str, boundary: str) -> int:
        """Event total at one boundary, summed over tags."""
        with self._lock:
            return sum(c[0] for (p, b, _t), c in self._cells.items()
                       if p == pipeline and b == boundary)

    def pipelines(self) -> List[str]:
        with self._lock:
            return sorted({p for (p, _b, _t) in self._cells})

    def snapshot(self) -> dict:
        """{pipeline: {boundary: {"events", "bytes", "tags"?}}} — plain
        nested dicts, directly comparable (two equal snapshots == no
        boundary crossed in between)."""
        with self._lock:
            cells = dict(self._cells)
        out: Dict[str, dict] = {}
        for (p, b, t), (ev, by) in sorted(cells.items()):
            brow = out.setdefault(p, {}).setdefault(
                b, {"events": 0, "bytes": 0})
            brow["events"] += ev
            brow["bytes"] += by
            if t:
                brow.setdefault("tags", {})[t] = {"events": ev, "bytes": by}
        return out

    def reset(self) -> None:
        """Tests only: forget every total."""
        with self._lock:
            self._cells.clear()


# ---------------------------------------------------------------------------
# module-global hook (chaos-plane idiom: one global read when off)

_ledger: Optional[EventLedger] = None
_auditor: Optional["ConservationAuditor"] = None


def is_on() -> bool:
    return _ledger is not None


def active_ledger() -> Optional[EventLedger]:
    return _ledger


def record(pipeline: str, boundary: str, events: int,
           nbytes: int = 0, tag: str = "") -> None:
    """Record one boundary crossing.  No-op (one global read + branch)
    while the ledger is disabled; hot paths with non-trivial argument
    expressions guard with ``if ledger.is_on():`` so the disabled cost
    stays one branch."""
    led = _ledger
    if led is None:
        return
    led.record(pipeline, boundary, events, nbytes, tag)


def enable() -> EventLedger:
    global _ledger
    if _ledger is None:
        _ledger = EventLedger()
    return _ledger


def disable() -> None:
    """Turn accounting off and retire the export records (a disabled
    ledger must not keep exporting stale totals)."""
    global _ledger
    stop_auditor()
    _ledger = None
    _retire_export_records()


def install_from_env(env=os.environ) -> bool:
    """``LOONG_LEDGER=1`` enables accounting; ``LOONG_LEDGER_AUDIT=1``
    enables accounting AND starts the continuous auditor.  Returns True
    when the ledger came on."""
    audit = env.get(ENV_AUDIT, "") not in ("", "0")
    on = audit or env.get(ENV_LEDGER, "") not in ("", "0")
    if not on:
        return False
    enable()
    if audit:
        try:
            interval = float(env.get(ENV_AUDIT_INTERVAL, "1.0"))
        except ValueError:
            interval = 1.0
        start_auditor(interval_s=interval)
    return True


# ---------------------------------------------------------------------------
# residual math

def residual_of(pipe_snap: dict, inflight: int = 0) -> int:
    """Conservation residual for one pipeline's snapshot row."""
    ev = lambda b: pipe_snap.get(b, {}).get("events", 0)  # noqa: E731
    sources = sum(ev(b) for b in SOURCE_BOUNDARIES)
    sinks = sum(ev(b) for b in SINK_BOUNDARIES)
    return sources - sinks - inflight


def residuals(snap: dict) -> Dict[str, int]:
    """Per-pipeline QUIESCED residuals over a full snapshot (inflight is
    provably zero at quiesce, the only instant residuals are evaluated).
    The "" pipeline row (boundary traffic with no pipeline attribution)
    is skipped — it has no entry boundary to conserve against."""
    return {p: residual_of(rows) for p, rows in snap.items() if p}


# ---------------------------------------------------------------------------
# live occupancy (observe-only, fail-soft — the exposition idiom)

def live_inflight() -> Optional[int]:
    """Approximate count of groups/items currently resident inside the
    agent (process queues, worker inboxes, device lanes, in-process
    groups, batchers, sender queues, retry heap, flusher-local queues).
    Units are deliberately mixed (groups vs items): the auditor only ever
    needs the ZERO test — residuals are evaluated exclusively at
    quiesce, where every term must be 0.

    Returns None when any occupancy probe raised: unknown occupancy must
    read as NOT quiesced (a partial total under-counts, and fail-soft
    here would convert a probe bug into a false CONSERVATION_RESIDUAL
    alarm — the one failure mode the auditor must never have).  The
    ``== 0`` quiesce tests treat None correctly (None != 0 → deferred)."""
    total = 0
    ok = True
    try:
        from ..pipeline import pipeline_manager as _pm
        mgr = _pm._active_manager
        if mgr is not None:
            pqm = mgr.process_queue_manager
            with mgr._lock:
                pipelines = list(mgr._pipelines.values())
                # loongtenant: old generations mid-drain left the name map
                # but still hold in-process groups / open windows /
                # flusher-local payloads — occupancy until the drain ends
                # (getattr: duck-typed test managers carry no drain list)
                pipelines.extend(getattr(mgr, "_draining", ()))
            for p in pipelines:
                if pqm is not None:
                    q = pqm.get_queue(p.process_queue_key)
                    if q is not None:
                        total += q.size()
                total += p._in_process_cnt
                agg_probe = getattr(p.aggregator, "open_window_rows", None)
                if agg_probe is not None:
                    # loongagg: open-window partials are pending rollup
                    # rows — occupancy, so the audit defers until the
                    # windows flush (drain force-closes them)
                    total += int(agg_probe())
                for f in p.flushers:
                    probe = getattr(f.plugin, "inflight_events", None)
                    if probe is not None:
                        total += int(probe())
    except Exception:  # noqa: BLE001
        ok = False
    try:
        from ..runner import processor_runner as _pr
        runner = _pr._active_runner
        if runner is not None:
            total += sum(runner.inbox_depths())
            total += sum(lane.pending_count() for lane in runner._lanes)
            # groups between a pop and their next counted station (a
            # descheduled worker's local variable is occupancy too)
            total += runner.in_hand_count()
    except Exception:  # noqa: BLE001
        ok = False
    try:
        from ..runner import flusher_runner as _fr
        fr = _fr._active_runner
        if fr is not None:
            with fr._retry_lock:
                total += len(fr._retry_heap)
            with fr.sqm._lock:
                queues = list(fr.sqm._queues.values())
            for q in queues:
                total += q.size()
    except Exception:  # noqa: BLE001
        ok = False
    try:
        from ..pipeline.batch.timeout_flush_manager import TimeoutFlushManager
        with TimeoutFlushManager.instance()._reg_lock:
            hooks = list(TimeoutFlushManager.instance()._batchers)
        for h in hooks:
            probe = getattr(h, "pending_events", None)
            if probe is not None:
                total += int(probe())
    except Exception:  # noqa: BLE001
        ok = False
    return total if ok else None


# ---------------------------------------------------------------------------
# lag watermarks

def lag_snapshot() -> Dict[str, Dict[str, float]]:
    """Per-pipeline oldest-resident ages in seconds: how long the oldest
    queued group (process side) / payload (sender side) has been waiting.
    Backpressure made visible per pipeline; exported as
    ``queue_lag_seconds`` / ``sender_queue_lag_seconds`` gauges."""
    out: Dict[str, Dict[str, float]] = {}

    def _slot(name: str) -> Dict[str, float]:
        return out.setdefault(name, {"process_queue": 0.0,
                                     "sender_queue": 0.0})

    try:
        from ..pipeline import pipeline_manager as _pm
        mgr = _pm._active_manager
        if mgr is not None and mgr.process_queue_manager is not None:
            pqm = mgr.process_queue_manager
            with mgr._lock:
                pipelines = list(mgr._pipelines.values())
            for p in pipelines:
                q = pqm.get_queue(p.process_queue_key)
                if q is None:
                    continue
                # an empty queue reports 0.0 (not absent): the per-pipeline
                # lag series stays continuous across drains
                age = getattr(q, "oldest_age", lambda: None)() or 0.0
                slot = _slot(p.name)
                slot["process_queue"] = max(slot["process_queue"], age)
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..runner import flusher_runner as _fr
        fr = _fr._active_runner
        if fr is not None:
            with fr.sqm._lock:
                queues = list(fr.sqm._queues.values())
            for q in queues:
                if not q.pipeline_name:
                    continue      # unnamed queue: no pipeline to attribute
                age = getattr(q, "oldest_age", lambda: None)() or 0.0
                slot = _slot(q.pipeline_name)
                slot["sender_queue"] = max(slot["sender_queue"], age)
    except Exception:  # noqa: BLE001
        pass
    return out


def max_lag_seconds() -> float:
    """The single worst oldest-resident age across every queue (bench's
    ``extra.conservation.max_queue_lag_seconds`` samples this)."""
    worst = 0.0
    for ages in lag_snapshot().values():
        for v in ages.values():
            worst = max(worst, v)
    return worst


# ---------------------------------------------------------------------------
# quiesce helpers (tests, bench, auditor)

def wait_quiesced(timeout: float = 30.0, poll: float = 0.15,
                  settle_rounds: int = 2) -> Optional[dict]:
    """Block until `settle_rounds` consecutive identical snapshots with
    zero live occupancy, then return that snapshot (None on timeout).
    Identical snapshots prove no boundary crossed between polls; zero
    occupancy proves nothing is parked mid-segment (retry backoff,
    batcher hold) — together: inflight == 0, residual is exact."""
    led = _ledger
    if led is None:
        return None
    deadline = time.monotonic() + timeout
    prev = None
    stable = 0
    while time.monotonic() < deadline:
        snap = led.snapshot()
        if snap == prev and live_inflight() == 0:
            stable += 1
            if stable >= settle_rounds:
                return snap
        else:
            stable = 0
        prev = snap
        time.sleep(poll)
    return None


def assert_conserved(timeout: float = 30.0, label: str = "") -> dict:
    """Test/bench helper: wait for quiesce, then require every pipeline's
    residual to be zero.  ``label`` names the checkpoint in failure
    messages (e.g. "seed 42 at the mid-storm checkpoint").  Returns the
    quiesced snapshot."""
    at = f" [{label}]" if label else ""
    snap = wait_quiesced(timeout=timeout)
    assert snap is not None, (
        f"ledger never quiesced{at} within {timeout}s "
        f"(live_inflight={live_inflight()})")
    rs = residuals(snap)
    bad = {p: r for p, r in rs.items() if r != 0}
    assert not bad, (
        f"conservation residual nonzero at quiesce{at}: {bad}; "
        f"snapshot={snap}")
    return snap


def device_memory_residual() -> Optional[int]:
    """loongxprof byte-conservation probe: ``ring_slots`` live bytes when
    the batch ring holds zero leased slots, else None (not evaluable —
    bytes are legitimately live while slots are leased).  Also None when
    the device plane / stream modules were never imported: absence of the
    subsystem is not evidence of a leak."""
    import sys as _sys
    _dp = _sys.modules.get("loongcollector_tpu.ops.device_plane")
    if _dp is None:
        return None
    _ds = _sys.modules.get("loongcollector_tpu.ops.device_stream")
    ring = getattr(_ds, "_ring", None) if _ds is not None else None
    if ring is not None and ring.totals().get("leased", 0) != 0:
        return None
    return int(_dp.mem_live_bytes("ring_slots"))


# ---------------------------------------------------------------------------
# continuous auditor

class ConservationAuditor:
    """Continuously audits quiesced snapshots; a nonzero residual raises
    ``AlarmType.CONSERVATION_RESIDUAL`` (once per episode per pipeline)
    and lands a ``ledger.residual`` flight-recorder entry with the
    per-boundary evidence an operator needs to start the triage
    (docs/observability.md#worked-triage-nonzero-residual)."""

    def __init__(self, ledger: EventLedger, interval_s: float = 1.0):
        self.ledger = ledger
        self.interval_s = max(0.05, float(interval_s))
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prev: Optional[dict] = None
        self._alarmed: set = set()
        # nonzero residuals seen on the PREVIOUS quiesced audit: an event
        # caught mid-hop between two counted stations (popped but not yet
        # handed to its pipeline) can fake a +1 residual for one audit, so
        # the alarm requires the same imbalance on two consecutive
        # quiesced audits — a real loss persists, a hop resolves
        self._suspect: Dict[str, int] = {}
        self.audits_total = 0
        self.quiesced_audits_total = 0
        self.residual_alarms_total = 0
        # loongxprof device-memory conservation: same two-consecutive-
        # sightings discipline as event residuals (a slot freed between
        # the ring read and the ledger read fakes a one-audit residual)
        self._mem_suspect: Optional[int] = None
        self._mem_alarmed = False
        self.device_memory_alarms_total = 0

    # -- one audit step (tests drive this directly) -------------------------

    def audit_once(self) -> Dict[str, int]:
        """Take one snapshot; when it matches the previous one and live
        occupancy is zero, evaluate residuals and alarm on nonzero.
        Returns the residuals evaluated this step ({} when not
        quiesced)."""
        self.audits_total += 1
        snap = self.ledger.snapshot()
        quiesced = (snap == self._prev and live_inflight() == 0)
        self._prev = snap
        if not quiesced:
            self._suspect.clear()
            self._mem_suspect = None
            return {}
        self.quiesced_audits_total += 1
        self._audit_device_memory()
        rs = residuals(snap)
        suspects: Dict[str, int] = {}
        for pipeline, res in rs.items():
            if res == 0:
                self._alarmed.discard(pipeline)
                continue
            if pipeline in self._alarmed:
                continue
            if self._suspect.get(pipeline) != res:
                suspects[pipeline] = res      # first sighting: confirm next
                continue
            self._alarmed.add(pipeline)
            self.residual_alarms_total += 1
            self._raise(pipeline, res, snap.get(pipeline, {}))
        self._suspect = suspects
        return rs

    def _audit_device_memory(self) -> None:
        """loongxprof: byte-conservation leg of a quiesced audit — with
        the event ledger quiesced AND the batch ring holding zero leased
        slots, the device-memory ledger's ``ring_slots`` family must read
        zero live bytes (every lease was matched by a return/forget).
        Other families legitimately hold pooled/cached footprint at
        quiesce (DFA tables, staging pools), so only the ring ties."""
        res = device_memory_residual()
        if res is None:
            self._mem_suspect = None
            return
        if res == 0:
            self._mem_alarmed = False
            self._mem_suspect = None
            return
        if self._mem_alarmed:
            return
        if self._mem_suspect != res:
            self._mem_suspect = res        # first sighting: confirm next
            return
        self._mem_alarmed = True
        self.device_memory_alarms_total += 1
        from ..prof import flight
        from .alarms import AlarmLevel, AlarmManager, AlarmType
        AlarmManager.instance().send_alarm(
            AlarmType.CONSERVATION_RESIDUAL,
            f"device-memory conservation broken: ring_slots ledger holds "
            f"{res} live bytes at quiesce with zero leased slots (an "
            f"unledgered free path; see /debug/status device_memory)",
            AlarmLevel.CRITICAL, pipeline="__device__",
            details={"residual_bytes": str(res),
                     "family": "ring_slots"})
        flight.record("ledger.device_memory_residual",
                      family="ring_slots", residual_bytes=res)

    def _raise(self, pipeline: str, res: int, rows: dict) -> None:
        from ..prof import flight
        from .alarms import AlarmLevel, AlarmManager, AlarmType
        totals = {b: r.get("events", 0) for b, r in sorted(rows.items())}
        AlarmManager.instance().send_alarm(
            AlarmType.CONSERVATION_RESIDUAL,
            f"event conservation broken: residual {res:+d} events at "
            f"quiesce (an unledgered loss path; see /debug/ledger)",
            AlarmLevel.CRITICAL, pipeline=pipeline,
            details={"residual": str(res),
                     "boundaries": repr(totals)})
        flight.record("ledger.residual", pipeline=pipeline,
                      residual=res, **{f"b_{b}": v
                                       for b, v in totals.items()})

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="ledger-auditor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.audit_once()
            except Exception:  # noqa: BLE001 — the auditor observes; it
                # must never take the agent down with it
                from ..utils.logger import get_logger
                get_logger("ledger").exception("conservation audit failed")


def start_auditor(interval_s: float = 1.0) -> ConservationAuditor:
    global _auditor
    if _auditor is None:
        _auditor = ConservationAuditor(enable(), interval_s=interval_s)
        _auditor.start()
    return _auditor


def stop_auditor() -> None:
    global _auditor
    if _auditor is not None:
        _auditor.stop()
        _auditor = None


def auditor() -> Optional[ConservationAuditor]:
    return _auditor


# ---------------------------------------------------------------------------
# export (Prometheus exposition + self-monitor pipeline)

_export_lock = threading.Lock()
_export_records: Dict[str, object] = {}


def _export_record(pipeline: str):
    rec = _export_records.get(pipeline)
    if rec is None:
        from .metrics import MetricsRecord
        with _export_lock:
            if _ledger is None:
                # disable() ran (or is mid-retire, which holds this same
                # lock): re-creating a record now would resurrect the
                # export and serve frozen totals forever
                return None
            rec = _export_records.get(pipeline)
            if rec is None:
                rec = _export_records[pipeline] = MetricsRecord(
                    category="ledger", labels={"pipeline": pipeline})
    return rec


def _retire_export_records() -> None:
    with _export_lock:
        for rec in _export_records.values():
            rec.mark_deleted()
        _export_records.clear()


def export_refresh() -> None:
    """Mirror ledger totals + residual + lag watermarks into per-pipeline
    gauge records (monotone gauges: the ledger's absolutes must survive
    the self-monitor's destructive counter drain).  Called by
    monitor/runtime_stats.refresh on the self-monitor cadence; no-op
    while the ledger is off."""
    led = _ledger
    if led is None:
        return
    snap = led.snapshot()
    lags = lag_snapshot()
    for pipeline in set(snap) | set(lags):
        if not pipeline:
            continue
        rec = _export_record(pipeline)
        if rec is None:      # disabled mid-refresh: stop mirroring
            return
        rows = snap.get(pipeline, {})
        for boundary, row in rows.items():
            rec.gauge("ledger_" + boundary + "_events").set(row["events"])
            rec.gauge("ledger_" + boundary + "_bytes").set(row["bytes"])
        rec.gauge("conservation_residual_events").set(
            residual_of(rows))
        ages = lags.get(pipeline, {})
        rec.gauge("queue_lag_seconds").set(ages.get("process_queue", 0.0))
        rec.gauge("sender_queue_lag_seconds").set(
            ages.get("sender_queue", 0.0))


def debug_document() -> dict:
    """The ``/debug/ledger`` page: full boundary matrix, per-pipeline
    residual, lag watermarks, live occupancy and auditor state."""
    led = _ledger
    doc: dict = {"enabled": led is not None}
    if led is None:
        return doc
    snap = led.snapshot()
    infl = live_inflight()
    doc["inflight_live"] = infl
    doc["pipelines"] = {
        p: {"boundaries": rows, "residual": residual_of(rows)}
        for p, rows in snap.items()}
    doc["lag"] = lag_snapshot()
    aud = _auditor
    if aud is not None:
        doc["auditor"] = {
            "interval_s": aud.interval_s,
            "audits_total": aud.audits_total,
            "quiesced_audits_total": aud.quiesced_audits_total,
            "residual_alarms_total": aud.residual_alarms_total,
        }
    return doc


def reset() -> None:
    """Tests only: zero totals (keeps the enabled state) and forget the
    auditor's quiesce baseline."""
    led = _ledger
    if led is not None:
        led.reset()
    if _auditor is not None:
        _auditor._prev = None
        _auditor._alarmed.clear()
        _auditor._suspect.clear()
