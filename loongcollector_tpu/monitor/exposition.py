"""Prometheus-text self-telemetry endpoint (off by default).

Reference analogue: the reference agent exposes its internal metrics for
scraping next to the self-monitor pipelines; here a stdlib
ThreadingHTTPServer serves ``GET /metrics`` rendering every live
MetricsRecord in text exposition format v0.0.4:

  * counters  → ``loong_<name>`` (NOTE: the self-monitor drains counters
    with delta semantics on its own cadence, so scraped counter values
    are deltas since the last self-monitor send, not process-lifetime
    cumulatives — documented in docs/observability.md);
  * gauges    → ``loong_<name>``;
  * histograms→ full ``_bucket{le=...}`` / ``_sum`` / ``_count`` series
    plus pre-computed ``_p50/_p90/_p99`` gauges for humans;
  * record labels (pipeline, plugin_id, sink...) become metric labels,
    with ``category`` always present.

Rendering never resets anything — scraping is read-only and safe to run
concurrently with the self-monitor drain.

loongprof (ISSUE 5) grows the endpoint into the agent's debug surface:

  * ``/healthz``       — liveness: 200 + uptime / worker-count JSON;
  * ``/debug/status``  — running status JSON (pipelines, queue depths,
    worker backlogs, breaker states, device-budget utilization, flight
    ring counts), assembled from observe-only module handles — the
    endpoint never constructs a subsystem to report on it;
  * ``/debug/pprof``   — the active profiler's folded stacks
    (flamegraph input; a comment line when profiling is off);
  * ``/debug/flight``  — the live flight-recorder ring as JSON (the same
    document a crash dump writes);
  * anything else      — 404 (the metrics page answers ONLY /metrics).

Activation: ``LOONG_EXPO_PORT=<port>`` env (application start) or
programmatic ``ExpositionServer(port).start()``; binds 127.0.0.1 unless
``LOONG_EXPO_HOST`` widens it.
"""

from __future__ import annotations

import http.server
import json
import math
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.logger import get_logger
from .metrics import WriteMetrics

log = get_logger("exposition")

ENV_PORT = "LOONG_EXPO_PORT"
ENV_HOST = "LOONG_EXPO_HOST"

_process_t0 = time.monotonic()

_PREFIX = "loong_"
_NAME_SAN = re.compile(r"[^a-zA-Z0-9_]")
_LABEL_SAN = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(raw: str) -> str:
    name = _NAME_SAN.sub("_", raw)
    if not name or name[0].isdigit():
        name = "_" + name
    return _PREFIX + name


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = []
    for k in sorted(labels):
        key = _LABEL_SAN.sub("_", str(k))
        val = str(labels[k]).replace("\\", "\\\\").replace(
            '"', '\\"').replace("\n", "\\n")
        parts.append(f'{key}="{val}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt(v: float) -> str:
    if v != v:                      # NaN
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render() -> str:
    """The whole live metric tree in text exposition format."""
    try:
        # loongledger gauges mirror on the self-monitor cadence; a scrape
        # refreshes them too (cheap, idempotent) so the conservation
        # series is live from the first scrape, not the first cadence
        from . import ledger as _ledger
        _ledger.export_refresh()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongslo freshness/burn gauges mirror the same way: a scrape is
        # never staler than one render
        from . import slo as _slo
        _slo.export_refresh()
    except Exception:  # noqa: BLE001
        pass
    by_name: Dict[Tuple[str, str], List[str]] = {}

    def emit(name: str, typ: str, line: str) -> None:
        by_name.setdefault((name, typ), []).append(line)

    for rec in WriteMetrics.instance().records():
        labels = dict(rec.labels)
        labels["category"] = rec.category
        # one snapshot per record: it already carries the histogram
        # percentiles, so only the bucket vectors need a separate read
        snap = rec.snapshot(reset_counters=False)
        for raw, value in snap["counters"].items():
            name = _metric_name(raw)
            emit(name, "counter", f"{name}{_label_str(labels)} {_fmt(value)}")
        for raw, value in snap["gauges"].items():
            name = _metric_name(raw)
            emit(name, "gauge", f"{name}{_label_str(labels)} {_fmt(value)}")
        for hist in rec.histograms():
            name = _metric_name(hist.name)
            hsnap = snap["histograms"].get(hist.name)
            if hsnap is None:      # registered after the snapshot above
                continue
            for le, cum in hist.buckets():
                le_label = 'le="%s"' % _fmt(le)
                emit(name, "histogram",
                     f"{name}_bucket{_label_str(labels, le_label)} {cum}")
            emit(name, "histogram",
                 f"{name}_sum{_label_str(labels)} {_fmt(hsnap['sum'])}")
            emit(name, "histogram",
                 f"{name}_count{_label_str(labels)} {hsnap['count']}")
            for q in ("p50", "p90", "p99"):
                qname = f"{name}_{q}"
                emit(qname, "gauge",
                     f"{qname}{_label_str(labels)} {_fmt(hsnap[q])}")
    out: List[str] = []
    for (name, typ) in sorted(by_name):
        out.append(f"# TYPE {name} {typ}")
        # insertion order, not lexical: histogram buckets must stay in
        # ascending `le` order ("+Inf" sorts lexically first)
        out.extend(by_name[(name, typ)])
    return "\n".join(out) + "\n"


def process_workers() -> int:
    """Active processor shard count, 0 when no runner is live."""
    from ..runner import processor_runner as _pr
    runner = _pr._active_runner
    return runner.thread_count if runner is not None else 0


def collect_status() -> dict:
    """The /debug/status document: a one-page answer to "what is this
    agent doing right now", assembled from observe-only handles.  Every
    section is fail-soft — a half-constructed subsystem (agent starting
    up, test harness) yields an absent section, never a 500."""
    doc: dict = {"time": int(time.time()),
                 "uptime_s": round(time.monotonic() - _process_t0, 1),
                 "pid": os.getpid()}
    try:
        from ..pipeline import pipeline_manager as _pm
        mgr = _pm._active_manager
        if mgr is not None:
            pqm = mgr.process_queue_manager
            with mgr._lock:
                items = list(mgr._pipelines.items())
            pipelines = {}
            for name, p in items:
                entry: dict = {"queue_key": p.process_queue_key}
                if pqm is not None:
                    q = pqm.get_queue(p.process_queue_key)
                    if q is not None:
                        entry["queue_depth"] = q.size()
                pipelines[name] = entry
            doc["pipelines"] = pipelines
            # loongtenant: per-tenant generation / last-reload / device-
            # budget-share rows — the multi-tenant control-plane page
            # (reload latency distributions live in the
            # pipeline_reload_seconds histogram on /metrics)
            doc["tenants"] = mgr.tenants_status()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongledger: per-pipeline conservation residual + lag watermarks
        # inline in the status page (the full boundary matrix lives at
        # /debug/ledger); absent while the ledger is off
        from . import ledger as _ledger
        led = _ledger.active_ledger()
        if led is not None:
            snap = led.snapshot()
            lags = _ledger.lag_snapshot()
            rows = doc.get("pipelines", {})
            for pname, prow in snap.items():
                if pname in rows:
                    rows[pname]["conservation_residual"] = \
                        _ledger.residual_of(prow)
            for pname, ages in lags.items():
                if pname in rows:
                    rows[pname]["queue_lag_seconds"] = round(
                        max(ages.values(), default=0.0), 3)
            doc["ledger"] = {
                "inflight_live": _ledger.live_inflight(),
                "residuals": _ledger.residuals(snap),
            }
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..runner import processor_runner as _pr
        runner = _pr._active_runner
        if runner is not None:
            doc["workers"] = {
                "count": runner.thread_count,
                "inbox_depths": runner.inbox_depths(),
                "lane_overlap": [round(x, 4)
                                 for x in runner.lane_overlap()],
            }
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..runner import flusher_runner as _fr
        fr = _fr._active_runner
        if fr is not None:
            doc["breakers"] = {br.name: br.state.name
                               for br in fr.breakers().values()}
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..ops.device_plane import DevicePlane
        plane = DevicePlane._instance    # observe-only: never construct
        if plane is not None:
            u = plane.utilization()
            doc["device"] = {k: (round(v, 6) if isinstance(v, float) else v)
                             for k, v in u.items()}
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongstream: ring occupancy, per-geometry padding waste, and the
        # width auto-tuner's chosen floors/deadline — the streaming plane's
        # "why is the device starving / what is padding costing" page
        from ..ops import device_stream as _ds
        ring = _ds._ring          # observe-only: never construct
        if ring is not None:
            tuner = _ds._tuner
            doc["streaming"] = {
                "depth": _ds.stream_depth(),
                "ring": ring.totals(),
                "geometries": ring.stats(),
                "tuner": tuner.chosen() if tuner is not None else None,
            }
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongmesh: chip lanes (breaker state, respill/fault counters,
        # per-chip occupancy and in-flight bytes) + every live sharded
        # kernel's psum telemetry, materialised here — off the hot path —
        # into the mesh_*_total counters.  The "which chip is sick / how
        # is the slice loaded" page.  Observe-only: never constructs the
        # router or a mesh.
        import sys as _sys
        _cl = _sys.modules.get("loongcollector_tpu.ops.chip_lanes")
        _mesh = _sys.modules.get("loongcollector_tpu.parallel.mesh")
        mesh_doc: dict = {}
        if _cl is not None:
            r = _cl.active_router()
            if r is not None and r.lane_count():
                mesh_doc.update(r.status())
        if _mesh is not None:
            ks = _mesh.mesh_status()
            if ks is not None:
                mesh_doc.update(ks)
        runner = None
        try:
            from ..runner import processor_runner as _pr
            runner = _pr._active_runner
        except Exception:  # noqa: BLE001
            pass
        if runner is not None and mesh_doc:
            mesh_doc["worker_chip_map"] = runner.chip_lane_map()
        if mesh_doc:
            doc["mesh"] = mesh_doc
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongfuse: fused-DFA compile stats — states/classes per set,
        # cache hit/miss, per-pattern demotions (the "why is grok slow /
        # did my pattern fall off the device tier" page)
        import sys as _sys
        _fuse = _sys.modules.get("loongcollector_tpu.ops.regex.fuse")
        if _fuse is not None:
            doc["fusion"] = _fuse.fusion_status()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongresident: per-program fused-dispatch rows (stages,
        # dispatch/demotion counts, geometries, cache hit/miss) — the
        # "is my pipeline really one dispatch per batch" page
        import sys as _sys
        _fp = _sys.modules.get("loongcollector_tpu.ops.fused_pipeline")
        if _fp is not None:
            doc["stage_fusion"] = _fp.stage_fusion_status()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongstruct: per-processor structural-parse fallback accounting
        # (the "is JSON/CSV parsing quietly per-row again" page) — absent
        # until a parse processor has processed rows
        import sys as _sys
        _pt = _sys.modules.get(
            "loongcollector_tpu.processor.parse_telemetry")
        if _pt is not None:
            parse_doc = _pt.status()
            if parse_doc:
                doc["parse"] = parse_doc
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..prof import flight as _flight
        rec = _flight.recorder()
        doc["flight"] = {"events": len(rec),
                         "recorded_total": rec.recorded_total(),
                         "dropped": rec.dropped_total()}
    except Exception:  # noqa: BLE001
        pass
    try:
        from .. import prof as _prof
        p = _prof.active_profiler()
        doc["profiler"] = {"active": p is not None,
                           "samples": p.samples_total() if p else 0}
    except Exception:  # noqa: BLE001
        pass
    try:
        from .. import recovery as _recovery
        rdoc = _recovery.status()
        if rdoc is not None:
            doc["recovery"] = rdoc
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongxprof: device-memory ledger — live/peak bytes per allocation
        # family (ring slots, resident columns, DFA tables, sharded staging,
        # side arenas).  Always-on (plain counters), so the section appears
        # whenever the device plane module has been imported.
        import sys as _sys
        _dp = _sys.modules.get("loongcollector_tpu.ops.device_plane")
        if _dp is not None:
            doc["device_memory"] = _dp.device_memory_status()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongxprof: per-family jit compile/cache accounting + recompile-
        # storm episodes — absent until the first watched_jit wrapper runs
        import sys as _sys
        _cw = _sys.modules.get("loongcollector_tpu.ops.compile_watch")
        if _cw is not None:
            cdoc = _cw.compile_status()
            if cdoc:
                doc["compile"] = cdoc
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongxprof: device timeline occupancy + per-(program, geometry)
        # dispatch decomposition; absent while LOONG_XPROF is off
        import sys as _sys
        _xp = _sys.modules.get("loongcollector_tpu.ops.xprof")
        if _xp is not None:
            xdoc = _xp.status()
            if xdoc is not None:
                doc["xprof"] = xdoc
    except Exception:  # noqa: BLE001
        pass
    return doc


#: every section collect_status() can emit — the parity contract the
#: tests hold /debug/status to (a new subsystem page must register here)
STATUS_SECTIONS = (
    "time", "uptime_s", "pid",
    "pipelines", "tenants", "ledger", "workers", "breakers",
    "device", "streaming", "mesh", "fusion", "stage_fusion", "parse",
    "flight", "profiler", "recovery",
    "device_memory", "compile", "xprof",
)


_INDEX = (b"loongcollector_tpu exposition endpoint\n"
          b"  /metrics       Prometheus text exposition\n"
          b"  /healthz       liveness (uptime + worker count)\n"
          b"  /debug/status  running-status JSON\n"
          b"  /debug/pprof   folded stacks (loongprof)\n"
          b"  /debug/flight  flight-recorder ring JSON\n"
          b"  /debug/ledger  event-conservation ledger JSON (loongledger)\n"
          b"  /debug/slo     freshness-SLO plane JSON (loongslo)\n"
          b"  /debug/timeline  unified host/device Chrome-trace JSON "
          b"(loongxprof)\n")

_PROM_CT = "text/plain; version=0.0.4; charset=utf-8"
_JSON_CT = "application/json; charset=utf-8"
_TEXT_CT = "text/plain; charset=utf-8"


class _Handler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, _PROM_CT, render().encode("utf-8"))
            elif path == "/healthz":
                doc = {"status": "ok", "pid": os.getpid(),
                       "uptime_s": round(time.monotonic() - _process_t0, 1),
                       "process_workers": process_workers()}
                self._reply(200, _JSON_CT,
                            (json.dumps(doc, sort_keys=True) + "\n").encode())
            elif path == "/debug/status":
                self._reply(200, _JSON_CT,
                            (json.dumps(collect_status(), sort_keys=True,
                                        default=str) + "\n").encode())
            elif path == "/debug/flight":
                from ..prof import flight as _flight
                doc = _flight.recorder().snapshot(reason="live")
                self._reply(200, _JSON_CT,
                            (json.dumps(doc, sort_keys=True,
                                        default=str) + "\n").encode())
            elif path == "/debug/ledger":
                from . import ledger as _ledger
                self._reply(200, _JSON_CT,
                            (json.dumps(_ledger.debug_document(),
                                        sort_keys=True,
                                        default=str) + "\n").encode())
            elif path == "/debug/slo":
                from . import slo as _slo
                self._reply(200, _JSON_CT,
                            (json.dumps(_slo.debug_document(),
                                        sort_keys=True,
                                        default=str) + "\n").encode())
            elif path == "/debug/timeline":
                # loongxprof: the unified host/device execution timeline,
                # loadable directly in Perfetto / chrome://tracing
                from ..trace.export import chrome_trace
                self._reply(200, _JSON_CT,
                            (json.dumps(chrome_trace(), sort_keys=True,
                                        default=str) + "\n").encode())
            elif path == "/debug/pprof":
                from .. import prof as _prof
                p = _prof.active_profiler()
                body = (p.folded_text() if p is not None
                        else "# profiler inactive (set LOONG_PROF=1)\n")
                self._reply(200, _TEXT_CT, body.encode("utf-8"))
            elif path == "/":
                # an index, NOT the metrics page: unknown or bare paths
                # must never masquerade as a scrape target
                self._reply(200, _TEXT_CT, _INDEX)
            else:
                self.send_response(404)
                self.end_headers()
        except Exception as e:  # noqa: BLE001 — a bad record must not 500-loop
            log.exception("exposition render failed")
            self.send_response(500)
            self.end_headers()
            self.wfile.write(repr(e).encode())

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrape traffic is not agent log news
        pass


class ExpositionServer:
    """Lifecycle wrapper; `port=0` binds an ephemeral port (tests)."""

    def __init__(self, port: int, host: str = "127.0.0.1"):
        self.host = host
        self.port = port
        self._server: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> bool:
        if self._server is not None:
            return True
        try:
            self._server = http.server.ThreadingHTTPServer(
                (self.host, self.port), _Handler)
        except OSError as e:
            log.error("exposition endpoint bind %s:%d failed: %s",
                      self.host, self.port, e)
            self._server = None
            return False
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="exposition", daemon=True)
        self._thread.start()
        log.info("exposition endpoint on http://%s:%d/metrics",
                 self.host, self.port)
        return True

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def start_from_env(env=os.environ) -> Optional[ExpositionServer]:
    """LOONG_EXPO_PORT activates the endpoint at application start."""
    raw = env.get(ENV_PORT)
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        log.error("bad %s=%r; exposition endpoint stays off", ENV_PORT, raw)
        return None
    server = ExpositionServer(port, env.get(ENV_HOST, "127.0.0.1"))
    return server if server.start() else None
