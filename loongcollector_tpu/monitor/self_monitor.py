"""Self-monitor server: agent metrics/alarms re-ingested as pipelines.

Reference: core/monitor/SelfMonitorServer.cpp:129,224,328 — a thread converts
metric records and alarms into event groups and pushes them into INTERNAL
collection pipelines consumed by input_internal_metrics /
input_internal_alarms (dogfooding: the agent observes itself through its own
data plane).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..models import PipelineEventGroup
from ..utils.logger import get_logger
from .alarms import AlarmManager
from .metrics import ReadMetrics

log = get_logger("self_monitor")

SEND_INTERVAL_S = 60.0


class SelfMonitorServer:
    _instance: Optional["SelfMonitorServer"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._lock = threading.Lock()
        # queue keys of the internal pipelines (set by the internal inputs)
        self._metrics_queue_key: Optional[int] = None
        self._alarms_queue_key: Optional[int] = None
        self._traces_queue_key: Optional[int] = None
        self.process_queue_manager = None
        self.interval_s = SEND_INTERVAL_S

    @classmethod
    def instance(cls) -> "SelfMonitorServer":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- registration by internal input plugins -----------------------------

    def set_metrics_pipeline(self, queue_key: Optional[int]) -> None:
        with self._lock:
            self._metrics_queue_key = queue_key

    def set_alarms_pipeline(self, queue_key: Optional[int]) -> None:
        with self._lock:
            self._alarms_queue_key = queue_key

    def set_traces_pipeline(self, queue_key: Optional[int]) -> None:
        """Route loongtrace spans/events to their own internal pipeline;
        when unset they ride the metrics pipeline (dogfooding either way)."""
        with self._lock:
            self._traces_queue_key = queue_key

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(target=self._run, name="self-monitor",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            if not self._running:
                return
            self._running = False
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        last = time.monotonic()
        while self._running:
            time.sleep(0.5)
            if time.monotonic() - last < self.interval_s:
                continue
            last = time.monotonic()
            try:
                self.send_once()
            except Exception:  # noqa: BLE001
                log.exception("self monitor send failed")

    # -- conversion ----------------------------------------------------------

    def send_once(self) -> None:
        pqm = self.process_queue_manager
        if pqm is None:
            return
        from .runtime_stats import refresh
        refresh()   # pull device-plane / scraper / eBPF gauges
        with self._lock:
            mkey, akey = self._metrics_queue_key, self._alarms_queue_key
            tkey = self._traces_queue_key
        # check queue validity BEFORE draining counters/alarms: the drain is
        # destructive, and the window where the queue is full is exactly the
        # window whose telemetry must not be lost — deltas keep accumulating
        # until the queue reopens.
        if mkey is not None and pqm.is_valid_to_push(mkey):
            group = self._metrics_group()
            if group is not None and not group.empty():
                pqm.push_queue(mkey, group)
        if akey is not None and pqm.is_valid_to_push(akey):
            group = self._alarms_group()
            if group is not None and not group.empty():
                pqm.push_queue(akey, group)
        # traces share the metrics pipeline unless routed to their own;
        # same destructive-drain rule: only drain into a pushable queue
        tkey = tkey if tkey is not None else mkey
        if tkey is not None and pqm.is_valid_to_push(tkey):
            group = self._traces_group()
            if group is not None and not group.empty():
                pqm.push_queue(tkey, group)

    @staticmethod
    def _metrics_group() -> Optional[PipelineEventGroup]:
        snaps = ReadMetrics.snapshot(reset_counters=True)
        if not snaps:
            return None
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        for snap in snaps:
            ev = group.add_metric_event(now)
            ev.set_name(sb.copy_string(snap["category"]))
            values = {}
            for k, v in snap["counters"].items():
                values[k] = float(v)
            for k, v in snap["gauges"].items():
                values[k] = float(v)
            for k, h in snap.get("histograms", {}).items():
                # flattened percentile trio + volume: the self-monitor
                # stream is multi-value metric events, not bucket vectors
                # (the exposition endpoint serves the full buckets)
                values[f"{k}_count"] = float(h["count"])
                values[f"{k}_p50"] = float(h["p50"])
                values[f"{k}_p90"] = float(h["p90"])
                values[f"{k}_p99"] = float(h["p99"])
                values[f"{k}_max"] = float(h["max"])
            if values:
                ev.set_multi_value(values)
            for k, v in snap["labels"].items():
                ev.set_tag(sb.copy_string(k), sb.copy_string(str(v)))
        group.set_tag(b"__source__", b"self_monitor")
        return group

    @staticmethod
    def _traces_group() -> Optional[PipelineEventGroup]:
        """Drain the active tracer into one event group (spans + timeline
        events as log events, __source__ = loongtrace).  No-op when
        tracing is disabled — the drain is destructive, so it only runs
        against a live tracer."""
        from .. import trace
        tracer = trace.active_tracer()
        if tracer is None:
            return None
        spans, events = tracer.drain()
        if not spans and not events:
            return None
        from ..trace.export import traces_to_group
        return traces_to_group(spans, events)

    @staticmethod
    def _alarms_group() -> Optional[PipelineEventGroup]:
        alarms = AlarmManager.instance().flush()
        if not alarms:
            return None
        group = PipelineEventGroup()
        sb = group.source_buffer
        now = int(time.time())
        for alarm in alarms:
            ev = group.add_log_event(now)
            for k, v in alarm.items():
                ev.set_content(sb.copy_string(k), sb.copy_string(v))
        group.set_tag(b"__source__", b"self_monitor")
        return group
