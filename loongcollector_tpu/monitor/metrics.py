"""Agent self-metrics.

Reference: core/monitor/MetricManager.h:33-94 — WriteMetrics holds a chain of
MetricsRecords (created by every queue/runner/plugin/pipeline); ReadMetrics
snapshots them for export.  Categories follow monitor/metric_constants/:
agent / runner / pipeline / component / plugin.

Concurrency contract (the PR-3 race fix): a record's registration dicts and
every counter's read-and-reset are independently locked, so

  * `snapshot(reset_counters=True)` can run concurrently with `add()` on
    any counter without losing increments — collect-and-reset is atomic
    per counter;
  * `snapshot()` can run concurrently with first-touch registration
    (`counter()` / `gauge()` / `histogram()`) without the dict-mutation
    RuntimeError the old unlocked iteration could hit (the chaos plane
    registers ``faults_<action>_total`` lazily mid-storm, exactly when the
    self-monitor snapshots).

Metric names are validated at registration: snake_case, and unique within
the record across metric kinds (a name that is a counter in one place and
a gauge in another would export two conflicting Prometheus types) — the
static side of the same rule is loonglint's `metric-naming` checker.
"""

from __future__ import annotations

import itertools
import math
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} is not snake_case ([a-z][a-z0-9_]*)")
    return name


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def collect(self) -> int:
        """Read and reset (delta semantics for export).  Atomic with
        respect to `add`: an increment either lands before the read (and
        is returned) or after the reset (and survives for the next
        collect) — never in between."""
        with self._lock:
            v = self._value
            self._value = 0
            return v


class Gauge:
    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self) -> float:
        return self._value


#: default histogram geometry: first bucket ≤ 1 µs, log2 growth, 40
#: buckets → top finite bound ≈ 550 s; latencies above that land in +Inf
HIST_BASE = 1e-6
HIST_BUCKETS = 40


class Histogram:
    """Lock-cheap fixed-bucket latency histogram (log2 boundaries).

    `observe(seconds)` computes the bucket index OUTSIDE the lock (frexp,
    no log call) and holds the lock only for four scalar updates, so hot
    paths (queue waits, device round-trips) pay a handful of ns beyond
    the lock itself.  Percentiles are bucket-upper-bound estimates —
    monotone and conservative (never under-report), which is what a
    regression gate wants.
    """

    __slots__ = ("name", "base", "n_buckets", "_counts", "_sum", "_count",
                 "_max", "_lock")

    def __init__(self, name: str, base: float = HIST_BASE,
                 n_buckets: int = HIST_BUCKETS):
        self.name = name
        self.base = float(base)
        self.n_buckets = int(n_buckets)
        self._counts = [0] * (self.n_buckets + 1)   # [+Inf] is the last slot
        self._sum = 0.0
        self._count = 0
        self._max = 0.0
        self._lock = threading.Lock()

    def _index(self, v: float) -> int:
        if v <= self.base:
            return 0
        m, e = math.frexp(v / self.base)    # v/base = m * 2**e, m in [0.5, 1)
        idx = e - 1 if m == 0.5 else e      # = ceil(log2(v/base))
        return idx if idx < self.n_buckets else self.n_buckets

    def observe(self, value: float) -> None:
        v = float(value)
        if v < 0.0:
            v = 0.0
        idx = self._index(v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    def upper_bound(self, idx: int) -> float:
        """The `le` boundary of bucket `idx` (inf for the overflow slot)."""
        if idx >= self.n_buckets:
            return math.inf
        return self.base * (2.0 ** idx)

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative (le, count) pairs, Prometheus histogram shape."""
        with self._lock:
            counts = list(self._counts)
        out: List[Tuple[float, int]] = []
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            out.append((self.upper_bound(i), cum))
        return out

    def _percentiles(self, counts: List[int], count: int,
                     mx: float, qs=(0.5, 0.9, 0.99)) -> List[float]:
        out = []
        for q in qs:
            if count == 0:
                out.append(0.0)
                continue
            target = q * count
            cum = 0
            val = mx
            for i, c in enumerate(counts):
                cum += c
                if cum >= target:
                    val = min(self.upper_bound(i), mx)
                    break
            out.append(val)
        return out

    def snapshot(self, reset: bool = False) -> dict:
        with self._lock:
            counts = list(self._counts)
            s, n, mx = self._sum, self._count, self._max
            if reset:
                self._counts = [0] * (self.n_buckets + 1)
                self._sum = 0.0
                self._count = 0
                self._max = 0.0
        p50, p90, p99 = self._percentiles(counts, n, mx)
        return {"count": n, "sum": s, "max": mx,
                "p50": p50, "p90": p90, "p99": p99}


class MetricsRecord:
    _ids = itertools.count()

    def __init__(self, category: str = "component",
                 labels: Optional[Dict[str, str]] = None):
        self.id = next(MetricsRecord._ids)
        self.category = category
        self.labels = labels or {}
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._reg_lock = threading.Lock()
        self._deleted = False
        WriteMetrics.instance().register(self)

    def _claim(self, name: str, kind: Dict) -> None:
        """Registration-time uniqueness (lock held): one name, one kind."""
        for d in (self._counters, self._gauges, self._histograms):
            if d is not kind and name in d:
                raise ValueError(
                    f"metric {name!r} already registered with a different "
                    "kind in this record")

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            _check_name(name)
            with self._reg_lock:
                c = self._counters.get(name)
                if c is None:
                    self._claim(name, self._counters)
                    c = self._counters[name] = Counter(name)
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            _check_name(name)
            with self._reg_lock:
                g = self._gauges.get(name)
                if g is None:
                    self._claim(name, self._gauges)
                    g = self._gauges[name] = Gauge(name)
        return g

    def histogram(self, name: str, base: float = HIST_BASE,
                  n_buckets: int = HIST_BUCKETS) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            _check_name(name)
            with self._reg_lock:
                h = self._histograms.get(name)
                if h is None:
                    self._claim(name, self._histograms)
                    h = self._histograms[name] = Histogram(
                        name, base, n_buckets)
        return h

    def histograms(self) -> List[Histogram]:
        with self._reg_lock:
            return list(self._histograms.values())

    def mark_deleted(self) -> None:
        self._deleted = True

    def snapshot(self, reset_counters: bool = False) -> dict:
        # copy the registration dicts under the lock so concurrent
        # first-touch registration can never mutate what we iterate
        with self._reg_lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._histograms.items())
        return {
            "category": self.category,
            "labels": dict(self.labels),
            "counters": {n: (c.collect() if reset_counters else c.value)
                         for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.snapshot(reset=reset_counters)
                           for n, h in hists},
            "time": int(time.time()),
        }


class WriteMetrics:
    _instance: Optional["WriteMetrics"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._records: List[MetricsRecord] = []
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "WriteMetrics":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, record: MetricsRecord) -> None:
        with self._lock:
            self._records.append(record)

    def gc_deleted(self) -> None:
        with self._lock:
            self._records = [r for r in self._records if not r._deleted]

    def records(self) -> List[MetricsRecord]:
        with self._lock:
            return [r for r in self._records if not r._deleted]


class ReadMetrics:
    """Snapshot side (reference ReadMetrics::UpdateMetrics)."""

    @staticmethod
    def snapshot(reset_counters: bool = False) -> List[dict]:
        return [r.snapshot(reset_counters) for r in WriteMetrics.instance().records()]


# ---------------------------------------------------------------------------
# process-lifetime shared instruments

_shared_lock = threading.Lock()
_shared_hists: Dict[tuple, Histogram] = {}


def shared_histogram(name: str, category: str = "component",
                     labels: Optional[Dict[str, str]] = None) -> Histogram:
    """One process-lifetime histogram per (name, category, labels) — the
    lazy module-level instrument pattern (device round-trips, queue
    waits) without each site hand-rolling its own double-checked lock.
    The backing record is created on first use and never retired."""
    key = (name, category, tuple(sorted((labels or {}).items())))
    h = _shared_hists.get(key)
    if h is None:
        with _shared_lock:
            h = _shared_hists.get(key)
            if h is None:
                rec = MetricsRecord(category=category, labels=labels)
                h = _shared_hists[key] = rec.histogram(name)
    return h
