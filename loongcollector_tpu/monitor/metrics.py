"""Agent self-metrics.

Reference: core/monitor/MetricManager.h:33-94 — WriteMetrics holds a chain of
MetricsRecords (created by every queue/runner/plugin/pipeline); ReadMetrics
snapshots them for export.  Categories follow monitor/metric_constants/:
agent / runner / pipeline / component / plugin.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional


class Counter:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def collect(self) -> int:
        """Read and reset (delta semantics for export)."""
        with self._lock:
            v = self._value
            self._value = 0
            return v


class Gauge:
    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self) -> float:
        return self._value


class MetricsRecord:
    _ids = itertools.count()

    def __init__(self, category: str = "component",
                 labels: Optional[Dict[str, str]] = None):
        self.id = next(MetricsRecord._ids)
        self.category = category
        self.labels = labels or {}
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._deleted = False
        WriteMetrics.instance().register(self)

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            c = Counter(name)
            self._counters[name] = c
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            g = Gauge(name)
            self._gauges[name] = g
        return g

    def mark_deleted(self) -> None:
        self._deleted = True

    def snapshot(self, reset_counters: bool = False) -> dict:
        return {
            "category": self.category,
            "labels": dict(self.labels),
            "counters": {n: (c.collect() if reset_counters else c.value)
                         for n, c in self._counters.items()},
            "gauges": {n: g.value for n, g in self._gauges.items()},
            "time": int(time.time()),
        }


class WriteMetrics:
    _instance: Optional["WriteMetrics"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._records: List[MetricsRecord] = []
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "WriteMetrics":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, record: MetricsRecord) -> None:
        with self._lock:
            self._records.append(record)

    def gc_deleted(self) -> None:
        with self._lock:
            self._records = [r for r in self._records if not r._deleted]

    def records(self) -> List[MetricsRecord]:
        with self._lock:
            return [r for r in self._records if not r._deleted]


class ReadMetrics:
    """Snapshot side (reference ReadMetrics::UpdateMetrics)."""

    @staticmethod
    def snapshot(reset_counters: bool = False) -> List[dict]:
        return [r.snapshot(reset_counters) for r in WriteMetrics.instance().records()]
