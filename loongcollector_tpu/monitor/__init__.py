from .metrics import (Counter, Gauge, Histogram, MetricsRecord, ReadMetrics,
                      WriteMetrics)
