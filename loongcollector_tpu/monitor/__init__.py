from .metrics import Counter, Gauge, MetricsRecord, ReadMetrics, WriteMetrics
