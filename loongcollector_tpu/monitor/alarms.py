"""Leveled, aggregated alarms.

Reference: core/monitor/AlarmManager.h:137-188 — alarms keyed by AlarmType
with warning/error/critical levels, aggregated (count per key) between
flushes, shipped through internal pipelines.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Dict, List, Optional, Tuple


class AlarmLevel(enum.IntEnum):
    WARNING = 0
    ERROR = 1
    CRITICAL = 2


class AlarmType(str, enum.Enum):
    """The reference's alarm taxonomy (core/monitor/AlarmManager.h:35-102),
    wire-name compatible so downstream alerting rules keyed on the alarm
    type string keep working, plus TPU-specific additions."""

    # config / control plane
    CONFIG_LOAD_FAIL = "CONFIG_LOAD_FAIL_ALARM"
    USER_CONFIG = "USER_CONFIG_ALARM"
    GLOBAL_CONFIG = "GLOBAL_CONFIG_ALARM"
    CONFIG_UPDATE = "CONFIG_UPDATE_ALARM"
    # loongtenant: a hot reload's new generation failed to init — the
    # manager ROLLED BACK to the previous generation, which keeps serving
    # (a bad fleet-wide YAML push degrades to "config not applied", never
    # to a collection outage)
    CONFIG_UPDATE_FAILED = "CONFIG_UPDATE_FAILED_ALARM"
    CATEGORY_CONFIG = "CATEGORY_CONFIG_ALARM"
    MULTI_CONFIG_MATCH = "MULTI_CONFIG_MATCH_ALARM"
    TOO_MANY_CONFIG = "TOO_MANY_CONFIG_ALARM"
    SAME_CONFIG = "SAME_CONFIG_ALARM"
    # file collection
    FILE_READ_FAIL = "READ_LOG_FAIL_ALARM"
    READ_LOG_DELAY = "READ_LOG_DELAY_ALARM"
    SKIP_READ_LOG = "SKIP_READ_LOG_ALARM"
    OPEN_LOGFILE_FAIL = "OPEN_LOGFILE_FAIL_ALARM"
    LOGFILE_PERMISSION = "LOGFILE_PERMINSSION_ALARM"
    LOGDIR_PERMISSION = "LOGDIR_PERMISSION_ALARM"
    LOG_TRUNCATE = "LOG_TRUNCATE_ALARM"
    SPLIT_LOG_FAIL = "SPLIT_LOG_FAIL_ALARM"
    FILE_READER_EXCEED = "FILE_READER_EXCEED_ALARM"
    OPEN_FILE_LIMIT = "OPEN_FILE_LIMIT_ALARM"
    DIR_EXCEED_LIMIT = "DIR_EXCEED_LIMIT_ALARM"
    STAT_LIMIT = "STAT_LIMIT_ALARM"
    MODIFY_FILE_EXCEED = "MODIFY_FILE_EXCEED_ALARM"
    INOTIFY_DIR_LIMIT = "INOTIFY_DIR_NUM_LIMIT_ALARM"
    REGISTER_INOTIFY_FAIL = "REGISTER_INOTIFY_FAIL_ALARM"
    INOTIFY_EVENT_OVERFLOW = "INOTIFY_EVENT_OVERFLOW_ALARM"
    READ_STOPPED_CONTAINER = "READ_STOPPED_CONTAINER_ALARM"
    INVALID_CONTAINER_PATH = "INVALID_CONTAINER_PATH_ALARM"
    # processing
    PARSE_LOG_FAIL = "PARSE_LOG_FAIL_ALARM"
    REGEX_MATCH = "REGEX_MATCH_ALARM"
    PARSE_TIME_FAIL = "PARSE_TIME_FAIL_ALARM"
    OUTDATED_LOG = "OUTDATED_LOG_ALARM"
    ENCODING_CONVERT = "ENCODING_CONVERT_ALARM"
    LOG_GROUP_PARSE_FAIL = "LOG_GROUP_PARSE_FAIL_ALARM"
    METRIC_GROUP_PARSE_FAIL = "METRIC_GROUP_PARSE_FAIL_ALARM"
    RELABEL_METRIC_FAIL = "RELABEL_METRIC_FAIL_ALARM"
    CAST_SENSITIVE_WORD = "CAST_SENSITIVE_WORD_ALARM"
    PROCESS_TOO_SLOW = "PROCESS_TOO_SLOW_ALARM"
    PROCESS_QUEUE_FULL = "PROCESS_QUEUE_FULL_ALARM"
    PROCESS_QUEUE_BUSY = "PROCESS_QUEUE_BUSY_ALARM"
    DROP_LOG = "DROP_LOG_ALARM"
    ENCRYPT_DECRYPT_FAIL = "ENCRYPT_DECRYPT_FAIL_ALARM"
    # sending
    SEND_FAIL = "SEND_DATA_FAIL_ALARM"
    SEND_QUOTA_EXCEED = "SEND_QUOTA_EXCEED_ALARM"
    SEND_COMPRESS_FAIL = "SEND_COMPRESS_FAIL_ALARM"
    COMPRESS_FAIL = "COMPRESS_FAIL_ALARM"
    SERIALIZE_FAIL = "SERIALIZE_FAIL_ALARM"
    SENDING_COSTS_TOO_MUCH_TIME = "SENDING_COSTS_TOO_MUCH_TIME_ALARM"
    LOG_GROUP_WAIT_TOO_LONG = "LOG_GROUP_WAIT_TOO_LONG_ALARM"
    DISCARD_DATA = "DISCARD_DATA_ALARM"
    DISCARD_SECONDARY = "DISCARD_SECONDARY_ALARM"
    SECONDARY_READ_WRITE = "SECONDARY_READ_WRITE_ALARM"
    SINK_CIRCUIT_OPEN = "SINK_CIRCUIT_OPEN_ALARM"
    # checkpoints / state
    CHECKPOINT_FAIL = "CHECKPOINT_ALARM"
    CHECKPOINT_V2 = "CHECKPOINT_V2_ALARM"
    EXACTLY_ONCE = "EXACTLY_ONCE_ALARM"
    LOAD_LOCAL_EVENT = "LOAD_LOCAL_EVENT_ALARM"
    # agent health
    CPU_LIMIT = "CPU_EXCEED_LIMIT_ALARM"
    MEM_LIMIT = "MEM_EXCEED_LIMIT_ALARM"
    AGENT_RESTART = "LOGTAIL_CRASH_ALARM"
    AGENT_CRASH_STACK = "LOGTAIL_CRASH_STACK_ALARM"
    INPUT_COLLECT_FAIL = "INPUT_COLLECT_ALARM"
    HOST_MONITOR = "HOST_MONITOR_ALARM"
    INNER_PROFILE = "INNER_PROFILE_ALARM"
    HOLD_ON_TOO_SLOW = "HOLD_ON_TOO_SLOW_ALARM"
    REGISTER_HANDLERS_TOO_SLOW = "REGISTER_HANDLERS_TOO_SLOW_ALARM"
    # TPU-specific
    DEVICE_PARSE_FALLBACK = "DEVICE_PARSE_FALLBACK_ALARM"
    DEVICE_BACKEND_DEGRADED = "DEVICE_BACKEND_DEGRADED_ALARM"
    MESH_SHARD_FALLBACK = "MESH_SHARD_FALLBACK_ALARM"
    # loongmesh: a chip lane's circuit opened — its shard respills to host
    # parsing while the rest of the mesh keeps running
    CHIP_LANE_OPEN = "CHIP_LANE_OPEN_ALARM"
    REGEX_TIER_DEMOTED = "REGEX_TIER_DEMOTED_ALARM"
    # loongstruct: a processor's sustained malformed-row rate pushed it
    # onto the counted per-row fallback path — correctness holds, but the
    # structural plane's throughput contract is broken for that pipeline
    PARSE_FALLBACK_DEGRADED = "PARSE_FALLBACK_DEGRADED_ALARM"
    # loongresident: a fused pipeline program demoted a chunk to the
    # per-stage dispatch path — answers identical, but that chunk paid N
    # round trips instead of one (docs/performance.md "Single-dispatch
    # pipeline fusion")
    FUSED_DEMOTED = "FUSED_DISPATCH_DEMOTED_ALARM"
    # loongledger: a quiesced conservation snapshot balanced to nonzero —
    # an event crossed into the agent and left without a ledgered exit
    CONSERVATION_RESIDUAL = "CONSERVATION_RESIDUAL_ALARM"
    # loongagg: the rollup key population hit its cardinality cap and
    # partials are being evicted (emitted early) — rollup windows for the
    # evicted keys are split, not lost
    AGG_WINDOW_EVICTION = "AGG_WINDOW_EVICTION_ALARM"
    # loongslo: a pipeline's freshness error budget is burning faster than
    # the multi-window multi-burn-rate policy tolerates — raised once per
    # episode with the stage-attributed latency-budget breakdown attached
    SLO_BURN_RATE = "SLO_BURN_RATE_ALARM"
    # loongxprof: a kernel family's jit compiles/minute crossed the storm
    # threshold (geometry churn — e.g. a flapping WidthAutoTuner bucket
    # forcing a fresh XLA compile per flap) — raised once per episode,
    # naming the churning family and geometry
    RECOMPILE_STORM = "RECOMPILE_STORM_ALARM"


class _AlarmRecord:
    __slots__ = ("type", "level", "message", "count", "first_time", "last_time",
                 "pipeline", "details")

    def __init__(self, typ: AlarmType, level: AlarmLevel, message: str,
                 pipeline: str):
        self.type = typ
        self.level = level
        self.message = message
        self.count = 0
        self.first_time = time.time()
        self.last_time = self.first_time
        self.pipeline = pipeline
        # structured payload (loongprof: flight-dump path, breach stack):
        # latest-wins across aggregation so a flush ships fresh pointers
        self.details: Dict[str, str] = {}


class AlarmManager:
    _instance: Optional["AlarmManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._records: Dict[Tuple[str, str, str], _AlarmRecord] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "AlarmManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def send_alarm(self, typ: AlarmType, message: str,
                   level: AlarmLevel = AlarmLevel.WARNING,
                   pipeline: str = "",
                   details: Optional[Dict[str, str]] = None) -> None:
        key = (typ.value, message[:128], pipeline)
        with self._lock:
            rec = self._records.get(key)
            created = rec is None
            if created:
                rec = _AlarmRecord(typ, level, message, pipeline)
                self._records[key] = rec
            rec.count += 1
            rec.last_time = time.time()
            if details:
                rec.details.update({str(k): str(v)
                                    for k, v in details.items()})
        # a NEW aggregation key is a notable event: mirror it into the
        # flight ring (OUTSIDE self._lock — loonglint blocking-under-lock
        # rule) so a crash dump carries the alarms that preceded it.
        # Repeats of an already-aggregated alarm ride the record's count
        # instead — a 1 Hz sustained breach must not evict the ring's
        # chaos/breaker/stall history with thousands of identical entries
        if created:
            from ..prof import flight
            flight.record("alarm", type=typ.value,
                          level=level.name.lower(),
                          message=message[:160], pipeline=pipeline)

    def flush(self) -> List[dict]:
        """Drain aggregated alarms as event dicts for the self-monitor
        pipeline."""
        with self._lock:
            records = list(self._records.values())
            self._records.clear()
        out = []
        for r in records:
            doc = {
                "alarm_type": r.type.value,
                "alarm_level": r.level.name.lower(),
                "alarm_message": r.message,
                "alarm_count": str(r.count),
                "pipeline": r.pipeline,
                "first_time": str(int(r.first_time)),
                "last_time": str(int(r.last_time)),
            }
            # structured details ride as extra content fields; the fixed
            # keys above always win a name collision
            for k, v in r.details.items():
                doc.setdefault(k, v)
            out.append(doc)
        return out

    def empty(self) -> bool:
        with self._lock:
            return not self._records
