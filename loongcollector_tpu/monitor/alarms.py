"""Leveled, aggregated alarms.

Reference: core/monitor/AlarmManager.h:137-188 — alarms keyed by AlarmType
with warning/error/critical levels, aggregated (count per key) between
flushes, shipped through internal pipelines.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Dict, List, Optional, Tuple


class AlarmLevel(enum.IntEnum):
    WARNING = 0
    ERROR = 1
    CRITICAL = 2


class AlarmType(str, enum.Enum):
    """Subset of the reference's 60+ alarm types, extensible."""

    CONFIG_LOAD_FAIL = "CONFIG_LOAD_FAIL_ALARM"
    PROCESS_QUEUE_FULL = "PROCESS_QUEUE_FULL_ALARM"
    SEND_FAIL = "SEND_FAIL_ALARM"
    SEND_QUOTA_EXCEED = "SEND_QUOTA_EXCEED_ALARM"
    PARSE_LOG_FAIL = "PARSE_LOG_FAIL_ALARM"
    FILE_READ_FAIL = "READ_LOG_FAIL_ALARM"
    CHECKPOINT_FAIL = "CHECKPOINT_ALARM"
    DISCARD_DATA = "DISCARD_DATA_ALARM"
    CPU_LIMIT = "CPU_EXCEED_LIMIT_ALARM"
    MEM_LIMIT = "MEM_EXCEED_LIMIT_ALARM"
    INPUT_COLLECT_FAIL = "INPUT_COLLECT_ALARM"
    DEVICE_PARSE_FALLBACK = "DEVICE_PARSE_FALLBACK_ALARM"  # TPU-specific
    AGENT_RESTART = "LOGTAIL_CRASH_ALARM"


class _AlarmRecord:
    __slots__ = ("type", "level", "message", "count", "first_time", "last_time",
                 "pipeline")

    def __init__(self, typ: AlarmType, level: AlarmLevel, message: str,
                 pipeline: str):
        self.type = typ
        self.level = level
        self.message = message
        self.count = 0
        self.first_time = time.time()
        self.last_time = self.first_time
        self.pipeline = pipeline


class AlarmManager:
    _instance: Optional["AlarmManager"] = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._records: Dict[Tuple[str, str, str], _AlarmRecord] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "AlarmManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def send_alarm(self, typ: AlarmType, message: str,
                   level: AlarmLevel = AlarmLevel.WARNING,
                   pipeline: str = "") -> None:
        key = (typ.value, message[:128], pipeline)
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = _AlarmRecord(typ, level, message, pipeline)
                self._records[key] = rec
            rec.count += 1
            rec.last_time = time.time()

    def flush(self) -> List[dict]:
        """Drain aggregated alarms as event dicts for the self-monitor
        pipeline."""
        with self._lock:
            records = list(self._records.values())
            self._records.clear()
        return [{
            "alarm_type": r.type.value,
            "alarm_level": r.level.name.lower(),
            "alarm_message": r.message,
            "alarm_count": str(r.count),
            "pipeline": r.pipeline,
            "first_time": str(int(r.first_time)),
            "last_time": str(int(r.last_time)),
        } for r in records]

    def empty(self) -> bool:
        with self._lock:
            return not self._records
