"""loongslo: the end-to-end freshness SLO plane.

Every event group admitted at the ledger's single B_INGEST hook
(ProcessQueueManager.push_queue) gets a monotonic-ns ingest stamp riding
its group METADATA (EventGroupMetaKey.INGEST_NS — columnar-safe: metadata
never touches the event columns).  Derived groups inherit the stamp via
``copy_meta_to`` (loonglint's ``stamp-propagation`` checker is the static
side of that contract); router fanout bumps a refcount (``note_fanout``)
exactly like the loongcrash ack watermark; aggregator rollups are minted
stampless and stamped at window close (``ensure_stamp`` in
``CollectionPipeline._send_direct``).

At every terminal-ack site the ack watermark already enumerates —
delivered (``send_ok``), durably spilled (``spill``), reason-tagged
discard (``drop``) — the stamp is observed: the ingest→terminal sojourn
lands in a per-(pipeline, outcome) log2 histogram ``event_to_flush_ms``
and the stamp is released from the outstanding registry.

``pipeline_freshness_seconds`` is now − the pipeline's oldest outstanding
stamp, BY CONSTRUCTION exactly 0.0 when nothing is outstanding: a
drained/idle pipeline can never read "now − ancient stamp".  The registry
is keyed by pipeline NAME, so a hot-reload generation handoff keeps the
series continuous (old-generation stamps stay visible until their
terminals, new-generation stamps join the same series).

On top, per-pipeline SLO objectives — sojourn p99 bound, freshness bound,
delivered-fraction target — are evaluated by the Google-SRE multi-window
multi-burn-rate rule scaled to agent timescales: a fast pair (default
30 s long / 5 s short at 14.4× burn) catches cliffs, a slow pair (120 s /
30 s at 6×) catches smolder; a trip additionally fires on a freshness
breach.  A trip raises ``AlarmType.SLO_BURN_RATE`` ONCE per episode with
a stage-attributed budget breakdown — deltas of the existing queue_wait /
stage / device_roundtrip / sender_queue_wait / sink_rtt histograms since
the last healthy evaluator tick, ranked by which hop ate the budget —
attached to the alarm details, the flight recorder, and ``/debug/slo``.
The episode clears (and re-arms) once both SHORT windows are back under
their thresholds and freshness is within bound.

Chaos-plane idiom: OFF by default, and every disabled hook is one
module-global read + branch — gated at ≤5% by scripts/slo_overhead.py in
lint.sh.  ``LOONG_SLO=1`` enables the plane and its evaluator thread.
"""

from __future__ import annotations

import heapq
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..models.event_group import EventGroupMetaKey

ENV_SLO = "LOONG_SLO"
ENV_INTERVAL = "LOONG_SLO_INTERVAL"
ENV_SOJOURN_MS = "LOONG_SLO_SOJOURN_P99_MS"
ENV_FRESHNESS_S = "LOONG_SLO_FRESHNESS_S"
ENV_TARGET = "LOONG_SLO_TARGET"

# terminal outcome taxonomy (docs/observability.md#freshness-slo-plane):
# every outcome mirrors a terminal the ack watermark already acks
OUTCOME_SEND_OK = "send_ok"
OUTCOME_SPILL = "spill"
OUTCOME_DROP = "drop"
OUTCOMES = (OUTCOME_SEND_OK, OUTCOME_SPILL, OUTCOME_DROP)

#: outstanding-stamp cap per pipeline — the same backstop shape as the ack
#: watermark's MAX_OUTSTANDING_SPANS: a terminal-starved pipeline (sink
#: down for hours) must bound registry memory; expiries are counted, and
#: an expired stamp's late terminal lands in stale_retires (not an error)
MAX_OUTSTANDING_STAMPS = 8192

#: per-second result ring horizon — must cover the longest burn window
RING_SECONDS = 600

#: hop attribution for the budget breakdown: existing histogram name →
#: budget hop.  sender_queue_wait and sink_rtt fold into one "sink" hop
#: (queue age + wire round-trips are both the egress leg's spend)
HOP_HISTOGRAMS = {
    "queue_wait_seconds": "queue",
    "stage_seconds": "stage",
    "device_roundtrip_seconds": "device",
    "sender_queue_wait_seconds": "sink",
    "sink_rtt_seconds": "sink",
}

_META_KEY = EventGroupMetaKey.INGEST_NS


class SloObjectives:
    """Per-pipeline SLO contract.  ``fast`` / ``slow`` are
    (long_window_s, short_window_s, burn_threshold) pairs — the classic
    multi-window multi-burn-rate shape, shrunk from SRE-book hours to
    agent seconds (a log agent's budget burns in minutes, not days)."""

    __slots__ = ("sojourn_p99_ms", "freshness_s", "target", "fast", "slow")

    def __init__(self, sojourn_p99_ms: float = 5000.0,
                 freshness_s: float = 30.0, target: float = 0.999,
                 fast: Tuple[float, float, float] = (30.0, 5.0, 14.4),
                 slow: Tuple[float, float, float] = (120.0, 30.0, 6.0)):
        self.sojourn_p99_ms = float(sojourn_p99_ms)
        self.freshness_s = float(freshness_s)
        self.target = min(float(target), 1.0 - 1e-9)
        self.fast = (float(fast[0]), float(fast[1]), float(fast[2]))
        self.slow = (float(slow[0]), float(slow[1]), float(slow[2]))

    def to_dict(self) -> dict:
        return {"sojourn_p99_ms": self.sojourn_p99_ms,
                "freshness_s": self.freshness_s,
                "target": self.target,
                "fast": list(self.fast), "slow": list(self.slow)}


class _PipeState:
    """Per-pipeline mutable state (all fields guarded by the plane lock
    except the firing/episode transitions' side effects, which run outside
    it)."""

    __slots__ = ("name", "heap", "ring", "ok_total", "bad_total",
                 "firing", "episodes", "stale_retires",
                 "forced_expirations", "objectives", "last_breakdown",
                 "last_stats")

    def __init__(self, name: str):
        self.name = name
        self.heap: List[int] = []          # outstanding stamp min-heap (ns)
        self.ring: Dict[int, List[int]] = {}   # second -> [ok, bad]
        self.ok_total = 0
        self.bad_total = 0
        self.firing = False
        self.episodes = 0
        self.stale_retires = 0
        self.forced_expirations = 0
        self.objectives: Optional[SloObjectives] = None
        self.last_breakdown: Optional[dict] = None
        self.last_stats: dict = {}


class SloPlane:
    def __init__(self, objectives: Optional[SloObjectives] = None):
        self.objectives = objectives or SloObjectives()
        self.max_outstanding = MAX_OUTSTANDING_STAMPS
        self._lock = threading.Lock()
        # ns stamp -> [pipeline, refcount]; stamps are uniquified at mint
        # so one ns addresses exactly one admit
        self._refs: Dict[int, List] = {}
        self._states: Dict[str, _PipeState] = {}
        self._rec_lock = threading.Lock()
        self._retired = False
        self._hist_records: Dict[Tuple[str, str], object] = {}
        self._hists: Dict[Tuple[str, str], object] = {}
        self._gauge_records: Dict[str, object] = {}
        # budget-breakdown baseline: hop-histogram (sum, count) at the last
        # healthy evaluator tick — a trip's breakdown is the delta since
        self._hop_baseline: Dict[str, Tuple[float, int]] = {}

    # -- stamp registry ------------------------------------------------------

    def _state_locked(self, pipeline: str) -> _PipeState:
        st = self._states.get(pipeline)
        if st is None:
            st = self._states[pipeline] = _PipeState(pipeline)
        return st

    def stamp(self, pipeline: str, group) -> None:
        """Mint + attach an ingest stamp (B_INGEST admit).  Runs BEFORE
        the queue push so a consumer can never observe a half-stamped
        group; a refused push must cancel_group."""
        ns = time.monotonic_ns()
        with self._lock:
            while ns in self._refs:     # uniquify: one ns == one admit
                ns += 1
            self._refs[ns] = [pipeline or "", 1]
            st = self._state_locked(pipeline or "")
            heapq.heappush(st.heap, ns)
            if len(st.heap) > self.max_outstanding:
                self._force_expire_locked(st)
        group.set_metadata(_META_KEY, str(ns))

    def ensure_stamp(self, pipeline: str, group) -> None:
        """Stamp only when missing — the aggregator-rollup exemption:
        rollup groups are minted stampless and enter the egress path at
        window close, which IS their ingest instant."""
        if group.get_metadata(_META_KEY) is None:
            self.stamp(pipeline, group)

    def _force_expire_locked(self, st: _PipeState) -> None:
        # drop lazily-dead heads first; then force-expire oldest live
        # stamps down to half capacity (counted — the freshness watermark
        # deliberately forgets what it can no longer afford to track)
        refs = self._refs
        while st.heap and st.heap[0] not in refs:
            heapq.heappop(st.heap)
        while len(st.heap) > self.max_outstanding // 2:
            ns = heapq.heappop(st.heap)
            if refs.pop(ns, None) is not None:
                st.forced_expirations += 1

    @staticmethod
    def stamp_of(group) -> Optional[int]:
        v = group.get_metadata(_META_KEY)
        if v is None:
            return None
        try:
            return int(str(v))
        except ValueError:
            return None

    def stamps_of(self, groups) -> Tuple[int, ...]:
        """Stamps a serialized payload carries — erasure-proof transport
        past the group→bytes boundary (the SenderQueueItem.spans shape)."""
        out = []
        for g in groups:
            ns = self.stamp_of(g)
            if ns is not None:
                out.append(ns)
        return tuple(out)

    def cancel_group(self, group) -> None:
        """Un-admit (refused queue push rolled back by the caller): the
        stamp never entered the agent, so it must not age the watermark."""
        ns = self.stamp_of(group)
        if ns is None:
            return
        with self._lock:
            self._refs.pop(ns, None)    # heap entry dies lazily

    def note_fanout(self, group, n: int) -> None:
        """Router matched ``n`` flushers: n−1 extra copies will each reach
        their own terminal — raise the refcount BEFORE any copy can ack
        (the ack-watermark fanout contract)."""
        ns = self.stamp_of(group)
        if ns is None or n <= 1:
            return
        with self._lock:
            entry = self._refs.get(ns)
            if entry is not None:
                entry[1] += n - 1

    # -- terminal observation ------------------------------------------------

    def observe_stamps(self, pipeline: str, stamps, outcome: str,
                       retire_only: bool = False,
                       now_ns: Optional[int] = None) -> None:
        if not stamps:
            return
        now = time.monotonic_ns() if now_ns is None else now_ns
        resolved = []
        with self._lock:
            for ns in stamps:
                entry = self._refs.get(ns)
                if entry is None:
                    # already released (fanout copy past the refcount,
                    # force-expired, or a replayed payload) — still a real
                    # delivery latency, attributed via the caller's hint
                    self._state_locked(pipeline or "").stale_retires += 1
                    resolved.append((pipeline or "", ns))
                    continue
                entry[1] -= 1
                if entry[1] <= 0:
                    del self._refs[ns]
                resolved.append((entry[0], ns))
        if retire_only:
            return
        now_s = time.monotonic()
        for pipe, ns in resolved:
            self.note_result(pipe, (now - ns) / 1e6, outcome, now_s=now_s)

    def observe_groups(self, pipeline: str, groups, outcome: str) -> None:
        self.observe_stamps(pipeline, self.stamps_of(groups), outcome)

    def retire_groups(self, groups) -> None:
        """Release stamps without a sojourn sample: the group's content
        was folded elsewhere (aggregator absorb, filtered-to-empty) — its
        DELIVERY is someone else's stamp."""
        self.observe_stamps("", self.stamps_of(groups), OUTCOME_DROP,
                            retire_only=True)

    def note_result(self, pipeline: str, sojourn_ms: float, outcome: str,
                    now_s: Optional[float] = None) -> None:
        """Feed one terminal result into the burn-rate ring + sojourn
        histogram.  "Bad" for the error budget = not delivered, OR
        delivered slower than the sojourn bound."""
        now = time.monotonic() if now_s is None else now_s
        sec = int(now)
        with self._lock:
            st = self._state_locked(pipeline or "")
            obj = st.objectives or self.objectives
            bad = (outcome != OUTCOME_SEND_OK
                   or sojourn_ms > obj.sojourn_p99_ms)
            slot = st.ring.get(sec)
            if slot is None:
                slot = st.ring[sec] = [0, 0]
                if len(st.ring) > RING_SECONDS:
                    cutoff = sec - RING_SECONDS
                    for s in [s for s in st.ring if s < cutoff]:
                        del st.ring[s]
            slot[1 if bad else 0] += 1
            if bad:
                st.bad_total += 1
            else:
                st.ok_total += 1
        h = self._hist(pipeline or "", outcome)
        if h is not None:
            h.observe(max(0.0, sojourn_ms))

    # -- freshness watermark -------------------------------------------------

    def _freshness_locked(self, st: _PipeState,
                          now_ns: Optional[int] = None) -> float:
        heap, refs = st.heap, self._refs
        while heap and heap[0] not in refs:
            heapq.heappop(heap)
        if not heap:
            return 0.0      # quiesced: hard zero by construction
        now = time.monotonic_ns() if now_ns is None else now_ns
        return max(0.0, (now - heap[0]) / 1e9)

    def freshness(self, pipeline: str) -> float:
        with self._lock:
            st = self._states.get(pipeline or "")
            if st is None:
                return 0.0
            return self._freshness_locked(st)

    def outstanding(self, pipeline: str) -> int:
        with self._lock:
            st = self._states.get(pipeline or "")
            if st is None:
                return 0
            heap, refs = st.heap, self._refs
            while heap and heap[0] not in refs:
                heapq.heappop(heap)
            return sum(1 for ns in heap if ns in refs)

    # -- burn-rate evaluation ------------------------------------------------

    def _window_locked(self, st: _PipeState, now_s: float,
                       window_s: float) -> Tuple[int, int]:
        lo = int(now_s) - int(window_s)
        ok = bad = 0
        for sec, slot in st.ring.items():
            if sec > lo:
                ok += slot[0]
                bad += slot[1]
        return ok, bad

    def _burn_locked(self, st: _PipeState, now_s: float, window_s: float,
                     obj: SloObjectives) -> float:
        ok, bad = self._window_locked(st, now_s, window_s)
        total = ok + bad
        if total == 0:
            return 0.0
        return (bad / total) / (1.0 - obj.target)

    def _stats_locked(self, st: _PipeState, now_s: float) -> dict:
        obj = st.objectives or self.objectives
        bf_long = self._burn_locked(st, now_s, obj.fast[0], obj)
        bf_short = self._burn_locked(st, now_s, obj.fast[1], obj)
        bs_long = self._burn_locked(st, now_s, obj.slow[0], obj)
        bs_short = self._burn_locked(st, now_s, obj.slow[1], obj)
        fresh = self._freshness_locked(st)
        ok, bad = self._window_locked(st, now_s, obj.slow[0])
        allowed = (ok + bad) * (1.0 - obj.target)
        if ok + bad == 0:
            remaining = 1.0
        elif allowed <= 0.0:
            remaining = 0.0 if bad else 1.0
        else:
            remaining = max(0.0, 1.0 - bad / allowed)
        return {"burn_fast_long": bf_long, "burn_fast_short": bf_short,
                "burn_slow_long": bs_long, "burn_slow_short": bs_short,
                "burn": max(bf_long, bs_long),
                "freshness_s": fresh,
                "budget_remaining": min(1.0, remaining),
                "window_ok": ok, "window_bad": bad}

    def evaluate_once(self, now_s: Optional[float] = None) -> Dict[str, dict]:
        """One evaluator tick: per pipeline, compute the window burns +
        freshness, run the episode state machine, refresh the exported
        gauges.  Manually drivable (tests pass ``now_s``); alarm/flight
        side effects run OUTSIDE the plane lock."""
        now = time.monotonic() if now_s is None else now_s
        raises: List[Tuple[str, int, dict]] = []
        clears: List[Tuple[str, int, dict]] = []
        results: Dict[str, dict] = {}
        with self._lock:
            for name, st in self._states.items():
                if not name:
                    continue    # unattributed results have no contract
                obj = st.objectives or self.objectives
                stats = self._stats_locked(st, now)
                trip = ((stats["burn_fast_long"] > obj.fast[2]
                         and stats["burn_fast_short"] > obj.fast[2])
                        or (stats["burn_slow_long"] > obj.slow[2]
                            and stats["burn_slow_short"] > obj.slow[2])
                        or stats["freshness_s"] > obj.freshness_s)
                calm = (stats["burn_fast_short"] <= obj.fast[2]
                        and stats["burn_slow_short"] <= obj.slow[2]
                        and stats["freshness_s"] <= obj.freshness_s)
                if trip and not st.firing:
                    st.firing = True
                    st.episodes += 1
                    raises.append((name, st.episodes, stats))
                elif st.firing and calm:
                    st.firing = False
                    clears.append((name, st.episodes, stats))
                stats["firing"] = st.firing
                stats["episodes"] = st.episodes
                st.last_stats = stats
                results[name] = stats
        for name, episode, stats in raises:
            self._raise(name, episode, stats)
        for name, episode, stats in clears:
            self._note_clear(name, episode, stats)
        if not raises:
            with self._lock:
                any_firing = any(st.firing for st in self._states.values())
            if not any_firing:
                # healthy tick: the NEXT trip's breakdown is the hop spend
                # accumulated since this instant
                self._hop_baseline = _hop_totals()
        self.export_gauges(results)
        return results

    # -- budget breakdown ----------------------------------------------------

    def budget_breakdown(self) -> dict:
        """Stage-attributed spend since the last healthy tick: per-hop
        delta-seconds of the existing latency histograms, ranked.  The
        dominant hop names which leg of the pipeline ate the budget."""
        cur = _hop_totals()
        base = self._hop_baseline
        hops: Dict[str, float] = {}
        hists: Dict[str, dict] = {}
        for name, (s, c) in sorted(cur.items()):
            b = base.get(name, (0.0, 0))
            ds = max(0.0, s - b[0])
            dc = max(0, c - b[1])
            hists[name] = {"delta_sum_s": round(ds, 6), "delta_count": dc}
            hop = HOP_HISTOGRAMS[name]
            hops[hop] = hops.get(hop, 0.0) + ds
        dominant = ""
        if hops and any(v > 0.0 for v in hops.values()):
            dominant = max(sorted(hops), key=lambda k: hops[k])
        return {"hops": {k: round(v, 6) for k, v in sorted(hops.items())},
                "histograms": hists, "dominant": dominant}

    def _raise(self, pipeline: str, episode: int, stats: dict) -> None:
        breakdown = self.budget_breakdown()
        with self._lock:
            st = self._states.get(pipeline)
            if st is not None:
                st.last_breakdown = breakdown
        # alarm + flight OUTSIDE self._lock (both take their own locks)
        from ..prof import flight
        from .alarms import AlarmLevel, AlarmManager, AlarmType
        dominant = breakdown.get("dominant", "") or "unknown"
        AlarmManager.instance().send_alarm(
            AlarmType.SLO_BURN_RATE,
            f"SLO error-budget burn: pipeline {pipeline!r} burning at "
            f"{stats['burn']:.1f}x (freshness {stats['freshness_s']:.2f}s); "
            f"budget went to the {dominant} hop — see /debug/slo",
            AlarmLevel.ERROR, pipeline=pipeline,
            details={"episode": str(episode),
                     "dominant_hop": dominant,
                     "burn_fast": f"{stats['burn_fast_long']:.2f}/"
                                  f"{stats['burn_fast_short']:.2f}",
                     "burn_slow": f"{stats['burn_slow_long']:.2f}/"
                                  f"{stats['burn_slow_short']:.2f}",
                     "freshness_s": f"{stats['freshness_s']:.3f}",
                     "budget_remaining":
                         f"{stats['budget_remaining']:.4f}",
                     "breakdown": json.dumps(breakdown, sort_keys=True)})
        flight.record("slo.burn_rate", pipeline=pipeline, episode=episode,
                      dominant_hop=dominant,
                      burn=round(stats["burn"], 3),
                      freshness_s=round(stats["freshness_s"], 3),
                      **{f"hop_{k}_s": v
                         for k, v in breakdown["hops"].items()})

    def _note_clear(self, pipeline: str, episode: int, stats: dict) -> None:
        from ..prof import flight
        flight.record("slo.burn_clear", pipeline=pipeline, episode=episode,
                      burn=round(stats["burn"], 3),
                      freshness_s=round(stats["freshness_s"], 3))

    # -- export --------------------------------------------------------------

    def _hist(self, pipeline: str, outcome: str):
        key = (pipeline, outcome)
        h = self._hists.get(key)
        if h is None:
            from .metrics import MetricsRecord
            with self._rec_lock:
                if self._retired:
                    # disable() ran: creating a record now would resurrect
                    # the export and serve a frozen histogram forever
                    return None
                h = self._hists.get(key)
                if h is None:
                    rec = MetricsRecord(
                        category="slo",
                        labels={"pipeline": pipeline, "outcome": outcome})
                    self._hist_records[key] = rec
                    h = self._hists[key] = rec.histogram("event_to_flush_ms")
        return h

    def _gauge_record(self, pipeline: str):
        rec = self._gauge_records.get(pipeline)
        if rec is None:
            from .metrics import MetricsRecord
            with self._rec_lock:
                if self._retired:
                    return None
                rec = self._gauge_records.get(pipeline)
                if rec is None:
                    rec = self._gauge_records[pipeline] = MetricsRecord(
                        category="slo", labels={"pipeline": pipeline})
        return rec

    def export_gauges(self, results: Optional[Dict[str, dict]] = None
                      ) -> None:
        """Mirror per-pipeline freshness/burn/budget into gauge records
        (monotone mirrors of plane state — they must survive the
        self-monitor's destructive counter drain)."""
        if results is None:
            now = time.monotonic()
            results = {}
            with self._lock:
                for name, st in self._states.items():
                    if not name:
                        continue
                    stats = self._stats_locked(st, now)
                    stats["firing"] = st.firing
                    stats["episodes"] = st.episodes
                    st.last_stats = stats
                    results[name] = stats
        with self._lock:
            outstanding = {}
            for name in results:
                st = self._states.get(name)
                if st is None:
                    continue
                heap, refs = st.heap, self._refs
                while heap and heap[0] not in refs:
                    heapq.heappop(heap)
                outstanding[name] = sum(1 for ns in heap if ns in refs)
        for name, stats in results.items():
            rec = self._gauge_record(name)
            if rec is None:
                return      # disabled mid-refresh: stop mirroring
            rec.gauge("pipeline_freshness_seconds").set(
                stats["freshness_s"])
            rec.gauge("slo_burn_rate").set(stats["burn"])
            rec.gauge("slo_error_budget_remaining").set(
                stats["budget_remaining"])
            rec.gauge("slo_burn_firing").set(1.0 if stats["firing"] else 0.0)
            rec.gauge("slo_burn_episodes").set(float(stats["episodes"]))
            rec.gauge("slo_outstanding_stamps").set(
                float(outstanding.get(name, 0)))

    def retire_records(self) -> None:
        with self._rec_lock:
            self._retired = True
            for rec in self._hist_records.values():
                rec.mark_deleted()
            for rec in self._gauge_records.values():
                rec.mark_deleted()
            self._hist_records.clear()
            self._hists.clear()
            self._gauge_records.clear()

    # -- config / introspection ----------------------------------------------

    def set_objectives(self, pipeline: str,
                       objectives: Optional[SloObjectives]) -> None:
        """Per-pipeline override (None restores the plane default)."""
        with self._lock:
            self._state_locked(pipeline or "").objectives = objectives

    def episode_count(self, pipeline: str) -> int:
        with self._lock:
            st = self._states.get(pipeline or "")
            return st.episodes if st is not None else 0

    def is_firing(self, pipeline: str) -> bool:
        with self._lock:
            st = self._states.get(pipeline or "")
            return st.firing if st is not None else False

    def debug_document(self) -> dict:
        now = time.monotonic()
        doc: dict = {"enabled": True,
                     "objectives": self.objectives.to_dict(),
                     "pipelines": {}}
        with self._lock:
            for name, st in sorted(self._states.items()):
                stats = self._stats_locked(st, now)
                heap, refs = st.heap, self._refs
                while heap and heap[0] not in refs:
                    heapq.heappop(heap)
                row = {
                    "freshness_s": round(stats["freshness_s"], 6),
                    "burn": {k: round(stats[k], 4)
                             for k in ("burn_fast_long", "burn_fast_short",
                                       "burn_slow_long", "burn_slow_short")},
                    "budget_remaining":
                        round(stats["budget_remaining"], 6),
                    "firing": st.firing,
                    "episodes": st.episodes,
                    "outstanding_stamps":
                        sum(1 for ns in heap if ns in refs),
                    "ok_total": st.ok_total,
                    "bad_total": st.bad_total,
                    "stale_retires": st.stale_retires,
                    "forced_expirations": st.forced_expirations,
                }
                if st.objectives is not None:
                    row["objectives"] = st.objectives.to_dict()
                if st.last_breakdown is not None:
                    row["last_breakdown"] = st.last_breakdown
                doc["pipelines"][name] = row
            doc["outstanding_total"] = len(self._refs)
        ev = _evaluator
        if ev is not None:
            doc["evaluator"] = {"interval_s": ev.interval_s,
                                "ticks_total": ev.ticks_total}
        return doc

    def reset(self) -> None:
        """Tests only: forget stamps, rings and episode state (keeps the
        enabled state and the export records)."""
        with self._lock:
            self._refs.clear()
            self._states.clear()
            self._hop_baseline = {}


def _hop_totals() -> Dict[str, Tuple[float, int]]:
    """Process-wide (sum_seconds, count) per budget-hop histogram name,
    merged across every live MetricsRecord (fail-soft: the breakdown is
    evidence, never a crash source)."""
    totals: Dict[str, List] = {}
    try:
        from .metrics import WriteMetrics
        for rec in WriteMetrics.instance().records():
            for h in rec.histograms():
                if h.name not in HOP_HISTOGRAMS:
                    continue
                snap = h.snapshot()
                t = totals.setdefault(h.name, [0.0, 0])
                t[0] += snap["sum"]
                t[1] += snap["count"]
    except Exception:  # noqa: BLE001
        pass
    return {k: (v[0], v[1]) for k, v in totals.items()}


# ---------------------------------------------------------------------------
# evaluator thread (the ConservationAuditor shape)

class SloEvaluator:
    def __init__(self, plane: SloPlane, interval_s: float = 1.0):
        self.plane = plane
        self.interval_s = max(0.05, float(interval_s))
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.ticks_total = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="slo-evaluator", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.ticks_total += 1
                self.plane.evaluate_once()
            except Exception:  # noqa: BLE001 — the evaluator observes; it
                # must never take the agent down with it
                from ..utils.logger import get_logger
                get_logger("slo").exception("slo evaluation failed")


# ---------------------------------------------------------------------------
# module-global hook (chaos-plane idiom: one global read when off)

_plane: Optional[SloPlane] = None
_evaluator: Optional[SloEvaluator] = None


def is_on() -> bool:
    return _plane is not None


def active_plane() -> Optional[SloPlane]:
    return _plane


def stamp_ingest(pipeline: str, group) -> None:
    plane = _plane
    if plane is None:
        return
    plane.stamp(pipeline, group)


def ensure_stamp(pipeline: str, group) -> None:
    plane = _plane
    if plane is None:
        return
    plane.ensure_stamp(pipeline, group)


def cancel_group(group) -> None:
    plane = _plane
    if plane is None:
        return
    plane.cancel_group(group)


def note_fanout(group, n: int) -> None:
    plane = _plane
    if plane is None:
        return
    plane.note_fanout(group, n)


def stamps_of(groups) -> Tuple[int, ...]:
    plane = _plane
    if plane is None:
        return ()
    return plane.stamps_of(groups)


def observe_stamps(pipeline: str, stamps, outcome: str) -> None:
    plane = _plane
    if plane is None or not stamps:
        return
    plane.observe_stamps(pipeline, stamps, outcome)


def observe_groups(pipeline: str, groups, outcome: str) -> None:
    plane = _plane
    if plane is None:
        return
    plane.observe_groups(pipeline, groups, outcome)


def retire_groups(groups) -> None:
    plane = _plane
    if plane is None:
        return
    plane.retire_groups(groups)


def freshness(pipeline: str) -> float:
    plane = _plane
    if plane is None:
        return 0.0
    return plane.freshness(pipeline)


def evaluate_once(now_s: Optional[float] = None) -> Dict[str, dict]:
    plane = _plane
    if plane is None:
        return {}
    return plane.evaluate_once(now_s)


def enable(objectives: Optional[SloObjectives] = None) -> SloPlane:
    global _plane
    if _plane is None:
        _plane = SloPlane(objectives)
    elif objectives is not None:
        _plane.objectives = objectives
    return _plane


def disable() -> None:
    """Turn the plane off and retire its export records (a disabled plane
    must not keep exporting stale freshness/burn series)."""
    global _plane
    stop_evaluator()
    plane = _plane
    _plane = None
    if plane is not None:
        plane.retire_records()


def start_evaluator(interval_s: float = 1.0) -> SloEvaluator:
    global _evaluator
    if _evaluator is None:
        _evaluator = SloEvaluator(enable(), interval_s=interval_s)
        _evaluator.start()
    return _evaluator


def stop_evaluator() -> None:
    global _evaluator
    if _evaluator is not None:
        _evaluator.stop()
        _evaluator = None


def evaluator() -> Optional[SloEvaluator]:
    return _evaluator


def install_from_env(env=os.environ) -> bool:
    """``LOONG_SLO=1`` enables the plane + evaluator; objective bounds via
    LOONG_SLO_SOJOURN_P99_MS / LOONG_SLO_FRESHNESS_S / LOONG_SLO_TARGET;
    evaluator cadence via LOONG_SLO_INTERVAL.  Returns True when the
    plane came on."""
    if env.get(ENV_SLO, "") in ("", "0"):
        return False

    def _f(key: str, default: float) -> float:
        try:
            return float(env.get(key, default))
        except ValueError:
            return default

    obj = SloObjectives(
        sojourn_p99_ms=_f(ENV_SOJOURN_MS, 5000.0),
        freshness_s=_f(ENV_FRESHNESS_S, 30.0),
        target=_f(ENV_TARGET, 0.999))
    enable(obj)
    start_evaluator(interval_s=_f(ENV_INTERVAL, 1.0))
    return True


def export_refresh() -> None:
    """Mirror plane state into the per-pipeline gauge records — called by
    monitor/runtime_stats.refresh (self-monitor cadence) and by the
    exposition renderer; no-op while the plane is off."""
    plane = _plane
    if plane is None:
        return
    plane.export_gauges()


def debug_document() -> dict:
    """The ``/debug/slo`` page."""
    plane = _plane
    if plane is None:
        return {"enabled": False}
    return plane.debug_document()


def reset() -> None:
    """Tests only: zero state (keeps the enabled state)."""
    plane = _plane
    if plane is not None:
        plane.reset()
