"""Runtime telemetry gauges for the self-monitor pipelines.

Reference analogue: core/monitor/metric_models + the per-runner metric
records the reference refreshes before each self-monitor send.  These
gauges surface the round-5 subsystems — the async device plane's in-flight
budget, the prometheus stream scraper's drop counter, the eBPF connection
table — so operators see device back-pressure and shedding in the same
internal metrics stream as everything else.
"""

from __future__ import annotations

from .metrics import MetricsRecord

_plane_rec = MetricsRecord(category="device_plane",
                           labels={"component": "device_plane"})
_prom_rec = MetricsRecord(category="prometheus_runner",
                          labels={"component": "prometheus"})
_ebpf_rec = MetricsRecord(category="ebpf_connections",
                          labels={"component": "ebpf"})
_mesh_rec = MetricsRecord(category="mesh_parse",
                          labels={"component": "sharded_plane"})
_shard_rec = MetricsRecord(category="processor_shards",
                           labels={"component": "loongshard"})
_prof_rec = MetricsRecord(category="profiler",
                          labels={"component": "loongprof"})
_xprof_rec = MetricsRecord(category="device_xprof",
                           labels={"component": "loongxprof"})


def refresh() -> None:
    """Pull current values into the gauge records (called by the
    self-monitor right before it snapshots).  Every section is fail-soft:
    telemetry must never take down the monitor thread."""
    try:
        from ..ops.device_plane import DevicePlane
        plane = DevicePlane._instance   # observe-only: never construct
        if plane is not None:
            _plane_rec.gauge("inflight_bytes").set(plane.inflight_bytes())
            _plane_rec.gauge("budget_bytes").set(plane.budget_bytes)
            _plane_rec.gauge("dispatched_total").set(
                plane.dispatched_total())
            # loongprof utilization accounting: occupancy integral,
            # submit-queue depth, and the "shard more vs device-bound"
            # counter (docs/observability.md)
            u = plane.utilization()
            _plane_rec.gauge("budget_held_fraction_now").set(
                u["held_fraction"])
            _plane_rec.gauge("budget_occupancy_avg").set(u["occupancy_avg"])
            _plane_rec.gauge("device_busy_fraction").set(u["busy_fraction"])
            # monotone integrals next to the lifetime averages: rate()
            # over a scrape pair recovers the RECENT fraction, which the
            # averages cannot show on a long-lived agent
            _plane_rec.gauge("budget_occupancy_integral_seconds").set(
                u["occupancy_integral_s"])
            _plane_rec.gauge("device_busy_seconds").set(u["busy_s"])
            _plane_rec.gauge("submit_queue_depth").set(
                u["submit_queue_depth"])
            _plane_rec.gauge("device_idle_while_backlogged_ms").set(
                u["idle_while_backlogged_ms"])
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongstream: batch-ring occupancy + padding waste + the auto-
        # tuner's live decisions, next to the plane budget they feed
        # (observe-only: a pipeline that never streamed exports nothing)
        from ..ops import device_stream as _ds
        ring = _ds._ring
        if ring is not None:
            totals = ring.totals()
            _plane_rec.gauge("ring_slots_leased").set(totals["leased"])
            _plane_rec.gauge("ring_slots_pooled").set(totals["pooled"])
            _plane_rec.gauge("batch_padding_fraction_lifetime").set(
                totals["padding_fraction"])
            _plane_rec.gauge("stream_depth").set(_ds.stream_depth())
        tuner = _ds._tuner
        if tuner is not None:
            _plane_rec.gauge("stream_flush_deadline_ms").set(
                tuner.flush_deadline_s() * 1000.0)
    except Exception:  # noqa: BLE001
        pass
    try:
        # psum'd mesh telemetry from the most recent sharded dispatch; the
        # int() materialisation happens HERE (monitor cadence), never on
        # the dispatch hot path
        from ..ops.regex.engine import _engine_cache, _engine_cache_lock
        with _engine_cache_lock:
            engines = list(_engine_cache.values())
        # LRU dict: most-recently-used engines live at the END — walk in
        # reverse so the gauges report the freshest mesh dispatch
        for eng in reversed(engines):
            sharded = getattr(eng, "_sharded", None)
            stats = getattr(sharded, "last_stats", None)
            if stats:
                _mesh_rec.gauge("devices").set(sharded.plane.num_devices)
                _mesh_rec.gauge("last_matched").set(int(stats["matched"]))
                _mesh_rec.gauge("last_events").set(int(stats["events"]))
                _mesh_rec.gauge("last_bytes").set(int(stats["bytes"]))
                # loongmesh: the monitor cadence is an off-hot-path fold
                # point for the queued psum stats (mesh_*_total counters)
                sharded.materialize_stats()
                break
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongmesh chip lanes: breaker states + respill totals in the
        # same stream (observe-only — the per-lane counters/gauges export
        # through each lane's own record; this is the fleet-level rollup)
        from ..ops import chip_lanes as _cl
        r = _cl.active_router()
        if r is not None and r.lane_count():
            _mesh_rec.gauge("chip_lanes").set(r.lane_count())
            _mesh_rec.gauge("chip_lanes_open").set(sum(
                1 for l in r.lanes
                if l.breaker_state().name != "CLOSED"))
            _mesh_rec.gauge("chip_lane_respilled_events").set(
                sum(l.respilled_events() for l in r.lanes))
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongshard: live shard backlog — an imbalanced affinity hash or a
        # wedged worker shows up here as one inbox holding the max depth
        from ..runner import processor_runner as _pr
        runner = _pr._active_runner       # observe-only: never construct
        if runner is not None:
            depths = runner.inbox_depths()
            _shard_rec.gauge("process_workers").set(runner.thread_count)
            _shard_rec.gauge("inbox_backlog_groups").set(sum(depths))
            _shard_rec.gauge("inbox_backlog_max").set(
                max(depths) if depths else 0)
            overlaps = runner.lane_overlap()
            _shard_rec.gauge("lane_overlap_ratio").set(
                sum(overlaps) / len(overlaps) if overlaps else 0.0)
        else:
            # no live runner: zero rather than freeze the last values — a
            # stopped runner must not export a phantom backlog (or a
            # phantom device-overlap signal)
            _shard_rec.gauge("process_workers").set(0)
            _shard_rec.gauge("inbox_backlog_groups").set(0)
            _shard_rec.gauge("inbox_backlog_max").set(0)
            _shard_rec.gauge("lane_overlap_ratio").set(0.0)
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongprof: sampler + flight-ring health in the same stream as
        # everything else (per-scope self_cost_ms counters export through
        # their own records — the profiler owns those)
        from .. import prof as _prof
        from ..prof import flight as _flight
        p = _prof.active_profiler()
        _prof_rec.gauge("prof_active").set(1.0 if p is not None else 0.0)
        _prof_rec.gauge("prof_samples_total").set(
            float(p.samples_total()) if p is not None else 0.0)
        rec = _flight.recorder()
        _prof_rec.gauge("flight_events").set(float(len(rec)))
        _prof_rec.gauge("flight_recorded_total").set(
            float(rec.recorded_total()))
        _prof_rec.gauge("flight_dropped_total").set(
            float(rec.dropped_total()))
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongledger: mirror boundary totals + residual + lag watermarks
        # into per-pipeline gauge records (no-op while the ledger is off)
        from . import ledger
        ledger.export_refresh()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongslo: mirror freshness/burn-rate gauges on the same cadence
        # (no-op while the SLO plane is off)
        from . import slo
        slo.export_refresh()
    except Exception:  # noqa: BLE001
        pass
    try:
        # loongxprof: device-memory ledger + timeline occupancy + compile
        # accounting rollup (per-family compile counters/histograms export
        # through compile_watch's own shared records — this is the fleet-
        # level "is anything storming / leaking" summary).  Observe-only:
        # sys.modules probes, never an import that constructs a plane.
        import sys as _sys
        _dp = _sys.modules.get("loongcollector_tpu.ops.device_plane")
        if _dp is not None:
            mem = _dp.device_memory_status()
            _xprof_rec.gauge("device_mem_live_bytes_total").set(
                float(mem["total_live_bytes"]))
            for fam, row in mem["families"].items():
                _xprof_rec.gauge(f"device_mem_live_bytes_{fam}").set(
                    float(row["live_bytes"]))
                _xprof_rec.gauge(f"device_mem_peak_bytes_{fam}").set(
                    float(row["peak_bytes"]))
        _cw = _sys.modules.get("loongcollector_tpu.ops.compile_watch")
        if _cw is not None:
            cdoc = _cw.compile_status()
            _xprof_rec.gauge("jit_families").set(float(len(cdoc)))
            _xprof_rec.gauge("jit_storm_episodes_total").set(float(
                sum(row["storm_episodes"] for row in cdoc.values())))
        _xp = _sys.modules.get("loongcollector_tpu.ops.xprof")
        if _xp is not None:
            xdoc = _xp.status()
            _xprof_rec.gauge("xprof_active").set(
                1.0 if xdoc is not None else 0.0)
            if xdoc is not None:
                _xprof_rec.gauge("xprof_dispatches_recorded").set(
                    float(xdoc["dispatches"]))
                _xprof_rec.gauge("xprof_dispatches_closed").set(
                    float(xdoc["closed"]))
                _xprof_rec.gauge("xprof_dispatches_dropped").set(
                    float(xdoc["dropped"]))
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..input.prometheus.scraper import PrometheusInputRunner
        runner = PrometheusInputRunner._instance
        if runner is not None:
            _prom_rec.gauge("dropped_groups").set(runner.dropped_groups)
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..input.ebpf.adapter import EventSource
        from ..input.ebpf.server import EBPFServer
        server = EBPFServer._instance
        if server is not None:
            netobs = server._managers.get(EventSource.NETWORK_OBSERVE)
            if netobs is not None:
                cm = netobs.connections
                _ebpf_rec.gauge("connections").set(cm.connection_count())
                _ebpf_rec.gauge("dropped_connections").set(cm.dropped_conns)
                _ebpf_rec.gauge("unmatched_responses").set(
                    cm.unmatched_responses)
            _ebpf_rec.gauge("process_cache_size").set(
                server.proc_tree.size())
            _ebpf_rec.gauge("process_cache_misses").set(
                server.proc_tree.misses)
    except Exception:  # noqa: BLE001
        pass
