"""Agent self-watchdog: CPU/RSS sampling, adaptive throttling, limit breach.

Reference: core/monitor/Monitor.cpp (LogtailMonitor) — periodic self
CPU/memory sampling; exceeding limits triggers suicide-and-restart; the
realtime CPU level feeds file-input flow control
(file_server/event_handler/LogInput.cpp:176-200).

Here the breach action is a callback (the Application requests a restart or
logs critically) and the CPU level is exported for the FileServer's adaptive
sleep.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from .. import prof
from ..prof import flight
from ..utils import flags
from ..utils.logger import get_logger
from .alarms import AlarmLevel, AlarmManager, AlarmType
from .metrics import MetricsRecord

log = get_logger("watchdog")

flags.DEFINE_FLAG_DOUBLE("cpu_usage_limit", "agent CPU cores limit", 2.0)
flags.DEFINE_FLAG_INT32("memory_usage_limit_mb", "agent RSS limit (MB)", 2048)


def _read_self_stat() -> tuple:
    """(utime+stime ticks, rss bytes) from /proc/self; comm-safe parse
    (field 2 may contain spaces — split after the last ')')."""
    with open("/proc/self/stat") as f:
        data = f.read()
    rest = data[data.rindex(")") + 2 :].split()
    ticks = int(rest[11]) + int(rest[12])
    rss_pages = int(rest[21])
    return ticks, rss_pages * os.sysconf("SC_PAGE_SIZE")


class LoongCollectorMonitor:
    def __init__(self, interval_s: float = 1.0,
                 on_limit_breach: Optional[Callable[[str], None]] = None):
        self.interval_s = interval_s
        self.on_limit_breach = on_limit_breach
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.metrics = MetricsRecord(category="agent", labels={})
        self.cpu_gauge = self.metrics.gauge("cpu_cores")
        self.mem_gauge = self.metrics.gauge("memory_rss_bytes")
        self.cpu_level = 0.0  # 0..1 fraction of the limit, for flow control
        self._breach_streak = 0
        self._last_dump_path: Optional[str] = None
        self._episode_details: Optional[dict] = None

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._run, name="watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
            self._thread = None
        # retire the record: a stopped watchdog exports nothing further
        # (loonglint metric-naming ownership rule)
        self.metrics.mark_deleted()

    def _run(self) -> None:
        hz = os.sysconf("SC_CLK_TCK")
        last_ticks, _ = _read_self_stat()
        last_t = time.monotonic()
        while self._running:
            time.sleep(self.interval_s)
            try:
                ticks, rss = _read_self_stat()
            except OSError:
                continue
            now = time.monotonic()
            dt = max(now - last_t, 1e-6)
            cores = (ticks - last_ticks) / hz / dt
            last_ticks, last_t = ticks, now
            self.cpu_gauge.set(cores)
            self.mem_gauge.set(rss)
            cpu_limit = flags.get_flag("cpu_usage_limit")
            mem_limit = flags.get_flag("memory_usage_limit_mb") * 1024 * 1024
            self.cpu_level = min(cores / cpu_limit, 1.0) if cpu_limit > 0 else 0.0
            self._check_limits(cores, rss, cpu_limit, mem_limit)

    def _breach_details(self, breach: str) -> dict:
        """loongprof: a breach alarm must be diagnosable post-mortem —
        attach the flight-recorder dump path and the breaching thread's
        sampled stack to the alarm payload.  The flight event, the stack
        sample AND the dump all happen once per breach EPISODE (streak
        start): a sustained breach at 1 Hz must neither flood the flight
        ring with identical entries nor pay an all-thread stack walk per
        sample on an agent already over its CPU limit."""
        if self._episode_details is not None:
            return dict(self._episode_details, breach=breach)
        stack = prof.hottest_stack()
        flight.record("watchdog.breach", breach=breach)
        self._last_dump_path = flight.dump(reason="watchdog_breach")
        details = {"flight_dump": self._last_dump_path or "",
                   "breach": breach}
        if stack is not None:
            details["breach_thread"] = stack[0]
            details["breach_stack"] = stack[1][-1600:]
        self._episode_details = details
        return dict(details)

    def _check_limits(self, cores: float, rss: int, cpu_limit: float,
                      mem_limit: int) -> None:
        breach = None
        if cpu_limit > 0 and cores > cpu_limit:
            breach = f"cpu {cores:.2f} cores > limit {cpu_limit}"
            log.warning("watchdog: %s", breach)
            # stable message so AlarmManager aggregation collapses samples
            AlarmManager.instance().send_alarm(
                AlarmType.CPU_LIMIT, "agent cpu over limit",
                AlarmLevel.ERROR, details=self._breach_details(breach))
        if rss > mem_limit > 0:
            breach = f"rss {rss>>20} MB > limit {mem_limit>>20} MB"
            log.warning("watchdog: %s", breach)
            AlarmManager.instance().send_alarm(
                AlarmType.MEM_LIMIT, "agent memory over limit",
                AlarmLevel.CRITICAL, details=self._breach_details(breach))
        if breach:
            self._breach_streak += 1
            # sustained breach (10 samples) triggers the restart action,
            # mirroring the reference's suicide-and-restart contract
            if self._breach_streak >= 10 and self.on_limit_breach:
                self.on_limit_breach(breach)
                self._breach_streak = 0
        else:
            self._breach_streak = 0
            # next episode gets a fresh dump, stack sample and flight entry
            self._last_dump_path = None
            self._episode_details = None
