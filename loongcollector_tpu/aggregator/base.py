"""Aggregator implementations.

Reference: pkg/pipeline/aggregator.go:24-51 (Add/Flush contract between the
processor and flusher stages) and the Go plugins it hosts —
plugins/aggregator/baseagg (pack logs into capped groups per logstore/topic),
aggregator/context (per-source grouping preserving order), shardhash
(SLS shard routing hash), metadatagroup (regroup by metadata keys).

TPU-native shape: aggregators regroup EVENTS across incoming groups into
output groups keyed by a per-event or per-group key. Output groups SHARE the
input group's SourceBuffer (the arena is refcounted), so regrouping is span
bookkeeping, never a byte copy. Columnar groups are keyed by group-level
tags/metadata only — splitting a columnar batch row-wise would defeat the
device-batch geometry, and per-row keys on the device path belong to the
router's device-side filter instead.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..models import EventGroupMetaKey, PipelineEventGroup
from ..pipeline.plugin.interface import Plugin, PluginContext


class Aggregator(Plugin):
    """add() may buffer; returns completed groups. flush() drains all."""

    def add(self, group: PipelineEventGroup) -> List[PipelineEventGroup]:
        raise NotImplementedError

    def flush(self) -> List[PipelineEventGroup]:
        return []


class _Bucket:
    __slots__ = ("group", "count", "born")

    def __init__(self, group: PipelineEventGroup):
        self.group = group
        self.count = 0
        self.born = time.monotonic()


_agg_metrics = None
_agg_metrics_lock = threading.Lock()


def _bucket_metrics():
    """Process-lifetime aggregator-stage counters (module-level record by
    design, like the chaos plane's): every bucket retirement path —
    MaxLogCount completion, arena rotation, timeout flush — is counted,
    which is what the ``unbounded-window`` loonglint rule requires of any
    window state in this package.  Locked lazy init: add() runs on
    multiple processor threads, and a racing double-construct would
    register an orphaned record with WriteMetrics forever."""
    global _agg_metrics
    if _agg_metrics is None:
        with _agg_metrics_lock:
            if _agg_metrics is None:
                from ..monitor.metrics import MetricsRecord
                _agg_metrics = MetricsRecord(
                    category="agent", labels={"component": "aggregator"})
    return _agg_metrics


class AggregatorBase(Aggregator):
    """Pack events into groups capped at MaxLogCount, keyed by topic tag
    (reference plugins/aggregator/baseagg: MaxLogCount=1024 per group)."""

    name = "aggregator_base"

    def __init__(self) -> None:
        super().__init__()
        self.max_count = 1024
        self.timeout_s = 3.0
        self._buckets: Dict[Tuple, _Bucket] = {}
        # add() runs on processor threads, flush_timeout() on thread 0's
        # timeout tick — same contract as Batcher, same lock discipline
        self._lock = threading.Lock()

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.max_count = int(config.get("MaxLogCount", 1024))
        self.timeout_s = float(config.get("TimeoutSecs", 3.0))
        return True

    @staticmethod
    def _tag_fingerprint(group: PipelineEventGroup) -> Tuple:
        """Groups with different tag sets must never merge — their events
        would ship under the first group's labels."""
        return tuple(sorted((bytes(k), bytes(v))
                            for k, v in group.tags.items()))

    def _key(self, group: PipelineEventGroup, ev) -> Tuple:
        return (self._tag_fingerprint(group),)

    def _group_meta(self, out: PipelineEventGroup, key: Tuple,
                    src: PipelineEventGroup) -> None:
        for k, v in src.tags.items():
            out.set_tag(k, v)
        for k, v in src._metadata.items():
            out.set_metadata(k, v)

    def add(self, group: PipelineEventGroup) -> List[PipelineEventGroup]:
        cols = group.columns
        if cols is not None and not group._events:
            # columnar batches pass through intact (see module docstring)
            return [group]
        done: List[PipelineEventGroup] = []
        with self._lock:
            for ev in group.events:
                key = self._key(group, ev)
                b = self._buckets.get(key)
                if b is None:
                    out = PipelineEventGroup(group.source_buffer)
                    self._group_meta(out, key, group)
                    b = self._buckets[key] = _Bucket(out)
                elif b.group.source_buffer is not group.source_buffer:
                    # events reference THEIR arena: a bucket can only hold
                    # events of one arena — rotate the bucket out
                    done.append(b.group)
                    out = PipelineEventGroup(group.source_buffer)
                    self._group_meta(out, key, group)
                    b = self._buckets[key] = _Bucket(out)
                b.group.events.append(ev)
                b.count += 1
                if b.count >= self.max_count:
                    done.append(b.group)
                    del self._buckets[key]
        if done:
            _bucket_metrics().counter(
                "agg_bucket_completions_total").add(len(done))
        return done

    def flush(self) -> List[PipelineEventGroup]:
        with self._lock:
            out = [b.group for b in self._buckets.values() if b.count]
            self._buckets.clear()
        return out

    def flush_timeout(self) -> List[PipelineEventGroup]:
        """Buckets older than the timeout complete (driven by the pipeline's
        timeout-flush hook, same cadence as batchers)."""
        now = time.monotonic()
        out: List[PipelineEventGroup] = []
        with self._lock:
            for key in list(self._buckets):
                b = self._buckets[key]
                if b.count and now - b.born >= self.timeout_s:
                    out.append(b.group)
                    del self._buckets[key]
        if out:
            _bucket_metrics().counter(
                "agg_bucket_timeout_flushes_total").add(len(out))
        return out


class AggregatorContext(AggregatorBase):
    """Per-source grouping preserving order (plugins/aggregator/context)."""

    name = "aggregator_context"

    def _key(self, group: PipelineEventGroup, ev) -> Tuple:
        return (str(group.get_metadata(EventGroupMetaKey.LOG_FILE_PATH)
                    or ""),
                str(group.get_metadata(EventGroupMetaKey.LOG_FILE_INODE)
                    or ""),
                self._tag_fingerprint(group))


class AggregatorMetadataGroup(AggregatorBase):
    """Regroup by event-field values (plugins/aggregator/metadatagroup):
    GroupMetadataKeys name LogEvent fields whose values key the output
    group and land in its tags."""

    name = "aggregator_metadata_group"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.keys = [k.encode() if isinstance(k, str) else k
                     for k in config.get("GroupMetadataKeys", [])]
        return bool(self.keys)

    def _key(self, group: PipelineEventGroup, ev) -> Tuple:
        vals = []
        get = getattr(ev, "get_content", None)
        for k in self.keys:
            v = get(k) if get is not None else None
            vals.append(bytes(v) if v is not None else b"")
        return tuple(vals)

    def _group_meta(self, out, key, src) -> None:
        super()._group_meta(out, key, src)
        for k, v in zip(self.keys, key):
            out.set_tag(k, v)


class AggregatorContentValueGroup(AggregatorMetadataGroup):
    """Group logs whose named content fields share values; the values
    become group tags (plugins/aggregator/contentvaluegroup).  `GroupKeys`
    names the fields; `Topic` optionally stamps the output groups;
    `ErrIfKeyNotFound` only affects logging in the reference — missing
    keys group under the empty value either way."""

    name = "aggregator_content_value_group"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        cfg = dict(config)
        cfg["GroupMetadataKeys"] = config.get("GroupKeys", [])
        if not AggregatorMetadataGroup.init(self, cfg, context):
            return False
        self.topic = str(config.get("Topic", "")).encode()
        return True

    def _group_meta(self, out, key, src) -> None:
        super()._group_meta(out, key, src)
        if self.topic:
            out.set_tag(b"__topic__", self.topic)


class AggregatorLogstoreRouter(AggregatorBase):
    """Route each log to a logstore by regex on one field's value
    (plugins/aggregator/logstorerouter): RouterRegex[i] sends matching
    logs toward RouterLogstore[i] (recorded as the output group's
    __logstore__ tag for FlusherSLS routing); non-matching logs keep the
    default logstore unless DropDisMatch."""

    name = "aggregator_logstore_router"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        if not AggregatorBase.init(self, config, context):
            return False
        import re as _re
        self.source_key = str(config.get("SourceKey", "content")).encode()
        regexes = config.get("RouterRegex", [])
        stores = config.get("RouterLogstore", [])
        if len(regexes) != len(stores) or not regexes:
            return False
        self.routes = [(_re.compile(str(r).encode()), str(s).encode())
                       for r, s in zip(regexes, stores)]
        self.drop_dismatch = bool(config.get("DropDisMatch", False))
        return True

    _DROP = object()

    def _route(self, ev) -> object:
        get = getattr(ev, "get_content", None)
        val = get(self.source_key) if get is not None else None
        if val is not None:
            data = bytes(val)
            for rx, store in self.routes:
                # unanchored, like the Go plugin's regexp.MatchString
                if rx.search(data):
                    return store
        return self._DROP if self.drop_dismatch else b""

    def _key(self, group: PipelineEventGroup, ev) -> Tuple:
        return (self._route(ev), self._tag_fingerprint(group))

    def add(self, group: PipelineEventGroup) -> List[PipelineEventGroup]:
        cols = group.columns
        if cols is not None and not group._events:
            group.materialize()     # routing needs per-event field access
        done: List[PipelineEventGroup] = []
        with self._lock:
            for ev in group.events:
                key = self._key(group, ev)
                if key[0] is self._DROP:
                    continue
                b = self._buckets.get(key)
                if b is None or \
                        b.group.source_buffer is not group.source_buffer:
                    if b is not None:
                        done.append(b.group)
                    out = PipelineEventGroup(group.source_buffer)
                    self._group_meta(out, key, group)
                    if key[0]:
                        out.set_tag(b"__logstore__", key[0])
                    b = self._buckets[key] = _Bucket(out)
                b.group.events.append(ev)
                b.count += 1
                if b.count >= self.max_count:
                    done.append(b.group)
                    del self._buckets[key]
        return done


class AggregatorShardHash(Aggregator):
    """Set the SLS shard-hash metadata from key field/tag values
    (plugins/aggregator/shardhash; FlusherSLS's shard routing)."""

    name = "aggregator_shardhash"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        super().init(config, context)
        self.keys = [k.encode() if isinstance(k, str) else k
                     for k in config.get("ShardHashKeys", [])]
        return bool(self.keys)

    def add(self, group: PipelineEventGroup) -> List[PipelineEventGroup]:
        parts = []
        for k in self.keys:
            v = group.get_tag(k)
            parts.append(bytes(v) if v is not None else b"")
        digest = hashlib.md5(b"_".join(parts)).hexdigest()
        group.set_metadata(EventGroupMetaKey.SOURCE_ID, digest)
        return [group]

    def flush(self) -> List[PipelineEventGroup]:
        return []


class AggregatorTelemetryRouter(AggregatorBase):
    """Route events to per-signal logstores by their TYPE.

    Covers aggregator_opentelemetry and aggregator_skywalking
    (plugins/aggregator/{opentelemetry,skywalking}): both fan one mixed
    stream into metrics/trace/log logstores.  The Go plugins infer the
    signal from the content-pair count (≤5 → metric, ≥19 → trace); this
    event model is typed, so MetricEvent/SpanEvent route exactly."""

    name = "aggregator_opentelemetry"
    default_prefix = "otlp"

    def init(self, config: Dict[str, Any], context: PluginContext) -> bool:
        if not AggregatorBase.init(self, config, context):
            return False
        p = self.default_prefix
        self.metrics_store = str(config.get("MetricsLogstore",
                                            f"{p}-metrics")).encode()
        self.trace_store = str(config.get("TraceLogstore",
                                          f"{p}-traces")).encode()
        self.log_store = str(config.get("LogLogstore",
                                        f"{p}-logs")).encode()
        self.topic = str(config.get("Topic", "")).encode()
        return True

    def _route(self, ev) -> bytes:
        from ..models.events import MetricEvent, SpanEvent
        if isinstance(ev, MetricEvent):
            return self.metrics_store
        if isinstance(ev, SpanEvent):
            return self.trace_store
        return self.log_store

    def _key(self, group: PipelineEventGroup, ev) -> Tuple:
        return (self._route(ev), self._tag_fingerprint(group))

    def _group_meta(self, out: PipelineEventGroup, key: Tuple,
                    src: PipelineEventGroup) -> None:
        AggregatorBase._group_meta(self, out, key, src)
        out.set_tag(b"__logstore__", key[0])
        if self.topic:
            out.set_tag(b"__topic__", self.topic)

    def add(self, group: PipelineEventGroup) -> List[PipelineEventGroup]:
        cols = group.columns
        if cols is not None and not group._events:
            group.materialize()     # routing needs per-event types
        return AggregatorBase.add(self, group)


class AggregatorSkywalking(AggregatorTelemetryRouter):
    """plugins/aggregator/skywalking — same router, skywalking-* stores."""

    name = "aggregator_skywalking"
    default_prefix = "skywalking"
