"""Aggregator stage (reference: pkg/pipeline/aggregator.go:24-51 + the Go
aggregator plugins, plugins/aggregator/*)."""


def register_all(registry) -> None:
    from .base import (AggregatorBase, AggregatorContentValueGroup,
                       AggregatorContext, AggregatorLogstoreRouter,
                       AggregatorMetadataGroup, AggregatorShardHash,
                       AggregatorSkywalking, AggregatorTelemetryRouter)

    registry.register_aggregator("aggregator_base", AggregatorBase)
    registry.register_aggregator("aggregator_context", AggregatorContext)
    registry.register_aggregator("aggregator_metadata_group",
                                 AggregatorMetadataGroup)
    registry.register_aggregator("aggregator_shardhash", AggregatorShardHash)
    registry.register_aggregator("aggregator_content_value_group",
                                 AggregatorContentValueGroup)
    registry.register_aggregator("aggregator_logstore_router",
                                 AggregatorLogstoreRouter)
    registry.register_aggregator("aggregator_opentelemetry",
                                 AggregatorTelemetryRouter)
    registry.register_aggregator("aggregator_skywalking",
                                 AggregatorSkywalking)
    registry.register_aggregator("aggregator_default", AggregatorBase)

    from .metric_rollup import AggregatorMetricRollup
    registry.register_aggregator("aggregator_metric_rollup",
                                 AggregatorMetricRollup)
