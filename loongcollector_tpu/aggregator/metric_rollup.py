"""aggregator_metric_rollup — columnar windowed metric rollups (loongagg).

The first real streaming aggregator (ROADMAP item 5): tumbling/sliding
event-time windows keyed per row by (metric name, configured label set),
folding sum/count/min/max/last plus the metrics.py-shaped log2-bucket
histogram — columnar end to end.  A batch's fold runs as ONE substrate
call (native ``lct_group_reduce`` — SIMD span hashing + hash segment
identity + row-order f64 reduce; numpy twin bit-identical; device twin
``ops/kernels/segment_reduce.SegmentReduceKernel`` — one dispatch per
``device_batch`` slot), so the per-row work is zero Python on every tier.
Only per-ROLLUP-KEY work (dict merge of batch partials into window state)
runs in the host language, and key cardinality is capped.

Windowing (slot granularity = SlideSecs, windows = WindowSecs wide,
``WindowSecs % SlideSecs == 0``; tumbling is SlideSecs == WindowSecs):

* the **watermark** is max event time seen minus AllowedLatenessSecs; a
  window [w0, w0+W) closes when the watermark passes its end — closed
  windows emit as fresh **columnar groups** (span columns over a new
  arena: name + labels + window bounds + aggregate columns) that ride the
  existing zero-copy serializers to any sink, including the
  remote-write-shaped payload on the prometheus http flusher;
* rows whose slot can no longer reach any open window are **late** —
  counted, reason-tagged in the ledger (``drop`` tag ``agg_late``), never
  silently absorbed;
* the key population across open windows is bounded by MaxKeys: inserting
  past the cap **evicts** the oldest open partial by emitting it early
  (split rollup, not data loss) — counted, alarmed
  (``AGG_WINDOW_EVICTION``).

Conservation (loongledger): the fold is an N→M contraction, which gets
its own boundaries instead of riding the generic aggregator delta —
``agg_in`` (rows entering), ``agg_fold`` (rows consumed by the fold: a
residual SINK), ``agg_emit`` (rollup rows minted at window close: a
residual SOURCE).  Open windows count as live occupancy
(``open_window_rows`` → ledger.live_inflight), so the auditor never
evaluates a residual while rollups are still pending, and
``flush()`` (pipeline drain, enable_full_drain_mode) force-closes every
window so drain always reaches a clean quiesce.

Chaos: the ``aggregator.flush`` point (ERROR + DELAY) gates the periodic
window-close path — an injected ERROR defers emission (windows stay open,
retried next add/timeout tick, counted ``agg_flush_faults_total``); the
drain-path flush consumes the fault non-raising and force-flushes anyway,
which is exactly the drain contract the storm test asserts.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import chaos
from ..chaos import ChaosFault
from ..models import ColumnarLogs, PipelineEventGroup, columnar_enabled
from ..models.event_group import SourceBuffer
from ..models.events import LogEvent, MetricEvent
from ..monitor import ledger
from ..monitor.metrics import MetricsRecord
from ..ops.kernels import segment_reduce as sr
from ..utils.logger import get_logger
from .base import Aggregator

log = get_logger("loongagg")

POINT_AGG_FLUSH = chaos.register_point("aggregator.flush")

_SUBSTRATES = ("auto", "native", "numpy", "device")


class _Partial:
    """One (slot, key)'s folded state.  Merging happens batch-partial →
    window-partial on BOTH the columnar and the dict path (the dict path
    builds the same per-add() batch partials first), so the two-level f64
    summation order is identical and the bench's value-identity assert is
    exact, not approximate."""

    __slots__ = ("sum", "count", "min", "max", "last", "hist")

    def __init__(self, hist_slots: int = 0):
        self.sum = 0.0
        self.count = 0
        self.min = 0.0
        self.max = 0.0
        self.last = 0.0
        self.hist = (np.zeros(hist_slots, dtype=np.int64)
                     if hist_slots else None)

    def merge(self, b_sum: float, b_count: int, b_min: float, b_max: float,
              b_last: float, b_hist) -> None:
        if b_count <= 0:
            return
        if self.count == 0:
            self.min = b_min
            self.max = b_max
        else:
            if b_min < self.min:
                self.min = b_min
            if b_max > self.max:
                self.max = b_max
        self.sum += b_sum
        self.count += b_count
        self.last = b_last
        if self.hist is not None and b_hist is not None:
            self.hist += b_hist

    def merge_partial(self, other: "_Partial") -> None:
        self.merge(other.sum, other.count, other.min, other.max,
                   other.last, other.hist)


class AggregatorMetricRollup(Aggregator):
    """See module docstring.  Config:

    WindowSecs / SlideSecs / AllowedLatenessSecs — window geometry;
    MetricNameKey (default ``__name__``) / ValueKey (default ``value``) /
    LabelKeys — the per-row key and value columns; MaxKeys — open-key
    cardinality cap (counted eviction past it); EmitHistogram + HistBase —
    the log2-bucket histogram column; IdleFlushSecs — wall-clock TTL that
    force-closes windows when the event-time watermark stalls (idle
    source); Substrate — auto|native|numpy|device (also
    ``LOONG_AGG_SUBSTRATE``)."""

    name = "aggregator_metric_rollup"
    supports_columnar = True
    #: loongledger: this aggregator books its own agg_in/agg_fold/agg_emit
    #: boundaries — the pipeline's generic aggregator delta accounting
    #: must not double-book the contraction
    ledger_self_accounting = True

    def __init__(self) -> None:
        super().__init__()
        self.window_s = 10
        self.slide_s = 10
        self.lateness_s = 0
        self.name_key = "__name__"
        self.value_key = "value"
        self.label_keys: List[str] = []
        self.max_keys = 65536
        self.emit_histogram = True
        self.hist_base = sr.HIST_BASE
        self.idle_flush_s = 5.0
        self.substrate = "auto"
        self._pipeline_name = ""
        self._lock = threading.Lock()
        # slot -> {key fields tuple -> _Partial}; every mutation below
        # keeps _n_keys in sync — the MaxKeys cap + counted eviction is
        # what the unbounded-window loonglint rule requires of any window
        # state in aggregator/
        self._windows: Dict[int, Dict[Tuple, _Partial]] = {}
        self._n_keys = 0
        self._next_close: Optional[int] = None
        self._max_ts = None  # type: Optional[int]
        self._last_event_wall = 0.0
        self._evict_alarmed = False
        self._device_kern = None
        # fold→merge key interning (BENCH_r11 device-cliff satellite):
        # the numpy/device substrates hand back the representatives' raw
        # key-matrix rows (BatchFold.rep_key_blob) — steady-state batches
        # look their merge key tuple up by those hash-key bytes instead
        # of re-slicing the arena and re-minting bytes per group per
        # batch.  Bounded: cleared past 4×MaxKeys (churned label sets).
        self._key_intern: Dict[bytes, Tuple] = {}
        # evicted partials staged between _merge_locked and the group
        # build at the end of the same add() call
        self._pending_evicted: List[Tuple[int, int, Tuple, _Partial]] = []
        self.metrics = MetricsRecord(
            category="plugin",
            labels={"plugin_type": self.name, "plugin_id": self.name})
        self._m_folded = self.metrics.counter("agg_folded_rows_total")
        self._m_invalid = self.metrics.counter("agg_invalid_rows_total")
        self._m_late = self.metrics.counter("agg_late_rows_total")
        self._m_emitted = self.metrics.counter("agg_emitted_rows_total")
        self._m_evicted = self.metrics.counter("agg_window_evictions_total")
        self._m_flush_faults = self.metrics.counter("agg_flush_faults_total")
        self._m_idle_flush = self.metrics.counter("agg_idle_flushes_total")
        self._g_open_keys = self.metrics.gauge("agg_open_keys")
        self._g_open_windows = self.metrics.gauge("agg_open_windows")
        self._g_lag = self.metrics.gauge("agg_window_lag_seconds")

    # ------------------------------------------------------------------

    def init(self, config: Dict[str, Any], context) -> bool:
        super().init(config, context)
        self.window_s = int(config.get("WindowSecs", 10))
        self.slide_s = int(config.get("SlideSecs", self.window_s))
        self.lateness_s = int(config.get("AllowedLatenessSecs", 0))
        self.name_key = str(config.get("MetricNameKey", "__name__"))
        self.value_key = str(config.get("ValueKey", "value"))
        self.label_keys = [str(k) for k in config.get("LabelKeys", [])]
        self.max_keys = int(config.get("MaxKeys", 65536))
        self.emit_histogram = bool(config.get("EmitHistogram", True))
        self.hist_base = float(config.get("HistBase", sr.HIST_BASE))
        self.idle_flush_s = float(config.get("IdleFlushSecs", 5.0))
        self.substrate = str(os.environ.get(
            "LOONG_AGG_SUBSTRATE", config.get("Substrate", "auto"))).lower()
        if self.substrate not in _SUBSTRATES:
            log.error("unknown Substrate %r", self.substrate)
            self.metrics.mark_deleted()   # failed init: nobody owns it
            return False
        if self.window_s <= 0 or self.slide_s <= 0 \
                or self.window_s % self.slide_s != 0 \
                or self.lateness_s < 0 or self.max_keys < 1:
            log.error("bad window geometry: window=%s slide=%s lateness=%s",
                      self.window_s, self.slide_s, self.lateness_s)
            self.metrics.mark_deleted()
            return False
        self._pipeline_name = getattr(context, "pipeline_name", "") or ""
        pipeline = getattr(context, "pipeline", None)
        if pipeline is not None:
            # record ownership: the pipeline retires it on release()
            pipeline._metric_records.append(self.metrics)
        return True

    # -- occupancy probe (ledger.live_inflight) -------------------------

    def open_window_rows(self) -> int:
        """Open (slot, key) partials across all windows, plus evicted
        partials staged for the next emission (a chaos-deferred flush
        must not fake a quiesce): nonzero while rollups are pending,
        which is what defers the conservation audit until they flush."""
        with self._lock:
            return self._n_keys + len(self._pending_evicted)

    # -- substrate fold -------------------------------------------------

    def _fold(self, arena, slots, key_offs, key_lens, val_offs, val_lens):
        n_hist = sr.N_HIST if self.emit_histogram else 1
        sub = self.substrate
        if sub in ("auto", "native"):
            out = sr.fold_batch_native(arena, slots, key_offs, key_lens,
                                       val_offs, val_lens,
                                       hist_base=self.hist_base,
                                       n_hist=n_hist)
            if out is not None:
                return out
            if sub == "native":
                log.warning("native substrate unavailable; numpy fold")
        if sub == "device":
            # per-instance kernel: swapping the module-global on an
            # n_hist mismatch would discard the jit cache every batch
            # when two pipelines disagree on EmitHistogram
            kern = self._device_kern
            if kern is None:
                kern = (sr.device_kernel() if n_hist == sr.N_HIST
                        else sr.SegmentReduceKernel(n_hist))
                self._device_kern = kern
            return kern.fold_batch(arena, slots, key_offs, key_lens,
                                   val_offs, val_lens,
                                   hist_base=self.hist_base)
        return sr.fold_batch_numpy(arena, slots, key_offs, key_lens,
                                   val_offs, val_lens,
                                   hist_base=self.hist_base, n_hist=n_hist)

    # -- add ------------------------------------------------------------

    def add(self, group: PipelineEventGroup) -> List[PipelineEventGroup]:
        # chaos gate OUTSIDE the state lock (DELAY sleeps here); an
        # injected ERROR defers this round's window close only — the fold
        # itself always proceeds, nothing is lost
        allow_flush = self._flush_gate()
        cols = group.columns
        out: List[PipelineEventGroup] = []
        with self._lock:
            if cols is not None and not group._events and columnar_enabled():
                self._add_columnar(group, cols)
            else:
                self._add_rows(group)
            self._last_event_wall = time.monotonic()
            if allow_flush:
                out = self._close_ready_locked()
            self._export_gauges_locked()
        return out

    def _ledger_rows(self, boundary: str, n: int, nbytes: int = 0,
                     tag: str = "") -> None:
        if n and ledger.is_on():
            ledger.record(self._pipeline_name, boundary, n, nbytes, tag=tag)

    def _add_columnar(self, group: PipelineEventGroup,
                      cols: ColumnarLogs) -> None:
        n = len(cols)
        if n == 0:
            return
        self._ledger_rows(ledger.B_AGG_IN, n, cols.total_bytes)
        arena = group.source_buffer.as_array()
        ts = np.asarray(cols.timestamps, dtype=np.int64)
        slots = ts // self.slide_s
        absent_o = np.zeros(n, dtype=np.int64)
        absent_l = np.full(n, -1, dtype=np.int32)

        def col(key):
            pair = cols.fields.get(key)
            if pair is None:
                return absent_o, absent_l
            return (np.asarray(pair[0], dtype=np.int64),
                    np.asarray(pair[1], dtype=np.int32))

        key_cols = [col(self.name_key)] + [col(k) for k in self.label_keys]
        key_offs = np.stack([c[0] for c in key_cols], axis=1)
        key_lens = np.stack([c[1] for c in key_cols], axis=1)
        voffs, vlens = col(self.value_key)
        # a row without a metric name is not a metric: force it onto the
        # counted invalid path (value len -1) before the fold
        vlens = np.where(key_lens[:, 0] < 0, np.int32(-1), vlens)
        fold = self._fold(arena, slots, key_offs, key_lens, voffs, vlens)
        n_invalid = fold.n_invalid
        n_late = 0
        buf = memoryview(np.ascontiguousarray(arena))
        K = 1 + len(self.label_keys)
        # one .tolist() per column: the per-GROUP merge loop then runs on
        # plain Python scalars (numpy scalar extraction per group was the
        # dominant cost at batch-cardinality ~ batch-size)
        rep = fold.rep_row
        rep_slots = slots[rep].tolist()
        rep_offs = key_offs[rep].tolist()
        rep_lens = key_lens[rep].tolist()
        sums_l = fold.sum.tolist()
        cnts_l = fold.count.tolist()
        mins_l = fold.min.tolist()
        maxs_l = fold.max.tolist()
        lasts_l = fold.last.tolist()
        hist = fold.hist if self.emit_histogram else None
        next_close = self._next_close
        merge = self._merge_locked
        intern = self._key_intern
        blob = fold.rep_key_blob
        if blob is not None and len(intern) > 4 * self.max_keys:
            self._key_intern.clear()
        for g in range(fold.n_groups):
            slot = rep_slots[g]
            cnt = cnts_l[g]
            if next_close is not None and slot < next_close:
                # every window this slot could feed has closed: late
                n_late += cnt
                continue
            key = None
            bkey = None
            if blob is not None:
                # reuse the fold's hash-key bytes: the blob row carries
                # (slot, lens, key bytes) — strip the 8-byte slot prefix
                # so one metric series interns to ONE tuple across
                # slots.  The per-key padded widths are part of the key:
                # blob bytes alone are ambiguous across batches whose
                # column widths differ (zero padding moves).
                bkey = (fold.key_widths, blob[g, 8:].tobytes())
                key = intern.get(bkey)
            if key is None:
                ko = rep_offs[g]
                kl = rep_lens[g]
                key = tuple(
                    (bytes(buf[ko[k]:ko[k] + kl[k]]) if kl[k] >= 0
                     else None)
                    for k in range(K))
                if bkey is not None:
                    intern[bkey] = key
            merge(slot, key, sums_l[g], cnt, mins_l[g], maxs_l[g],
                  lasts_l[g], hist[g] if hist is not None else None)
        self._note_rows_locked(int(ts.max()) if n else None,
                               n - n_invalid - n_late, n_invalid, n_late)

    def _add_rows(self, group: PipelineEventGroup) -> None:
        """Per-event dict path (dict mode / already-materialized groups):
        identical two-level fold — batch partials first, merged into the
        window state with the same merge the columnar path uses."""
        events = group.events
        if not events:
            return
        self._ledger_rows(ledger.B_AGG_IN, len(events), group.data_size())
        name_b = self.name_key.encode()
        value_b = self.value_key.encode()
        label_bs = [k.encode() for k in self.label_keys]
        hist_slots = sr.N_HIST if self.emit_histogram else 0
        batch: Dict[Tuple[int, Tuple], _Partial] = {}
        n_invalid = 0
        n_late = 0
        max_ts = None
        for ev in events:
            ts = int(ev.timestamp)
            max_ts = ts if max_ts is None else max(max_ts, ts)
            slot = ts // self.slide_s
            if isinstance(ev, MetricEvent):
                nm = bytes(ev.name) if ev.name is not None else None
                v = (None if ev.value.is_multi()
                     else float(ev.value.value or 0.0))
                labels = tuple(
                    bytes(t) if (t := ev.get_tag(k)) is not None else None
                    for k in label_bs)
            elif isinstance(ev, LogEvent):
                nv = ev.get_content(name_b)
                nm = bytes(nv) if nv is not None else None
                vv = ev.get_content(value_b)
                v = None
                if vv is not None:
                    tok = bytes(vv).strip(b" \t")
                    if sr._VALUE_RE.match(tok):
                        v = float(tok)
                labels = tuple(
                    bytes(c) if (c := ev.get_content(k)) is not None
                    else None for k in label_bs)
            else:
                nm, v, labels = None, None, ()
            if v is None or nm is None:
                n_invalid += 1
                continue
            if self._next_close is not None and slot < self._next_close:
                n_late += 1
                continue
            key = (slot, (nm,) + labels)
            p = batch.get(key)
            if p is None:
                p = batch[key] = _Partial(hist_slots)
            if self.emit_histogram:
                bh = np.zeros(hist_slots, dtype=np.int64)
                bh[sr.hist_bucket_scalar(v, self.hist_base, hist_slots)] = 1
            else:
                bh = None
            p.merge(v, 1, v, v, v, bh)
        for (slot, key), p in batch.items():
            self._merge_locked(slot, key, p.sum, p.count, p.min, p.max,
                               p.last, p.hist)
        self._note_rows_locked(max_ts, len(events) - n_invalid - n_late,
                               n_invalid, n_late)

    def _note_rows_locked(self, max_ts: Optional[int], folded: int,
                          invalid: int, late: int) -> None:
        if max_ts is not None:
            self._max_ts = (max_ts if self._max_ts is None
                            else max(self._max_ts, max_ts))
        if folded:
            self._m_folded.add(folded)
            self._ledger_rows(ledger.B_AGG_FOLD, folded)
        if invalid:
            self._m_invalid.add(invalid)
            # rows without a parseable (name, value) shape are terminally
            # dropped, reason-tagged — never silently absorbed
            log.debug("dropping %d invalid metric rows", invalid)
            self._ledger_rows(ledger.B_DROP, invalid, tag="agg_invalid")
        if late:
            self._m_late.add(late)
            log.debug("dropping %d late metric rows (watermark passed)",
                      late)
            self._ledger_rows(ledger.B_DROP, late, tag="agg_late")

    def _merge_locked(self, slot: int, key: Tuple, b_sum: float,
                      b_count: int, b_min: float, b_max: float,
                      b_last: float, b_hist) -> None:
        d = self._windows.get(slot)
        p = d.get(key) if d is not None else None
        if p is None:
            if self._n_keys >= self.max_keys:
                # evict FIRST (it may retire the slot's whole dict), then
                # re-resolve the slot so the insert lands in live state
                self._evict_one_locked()
            d = self._windows.setdefault(slot, {})
            p = d[key] = _Partial(
                sr.N_HIST if self.emit_histogram else 0)
            self._n_keys += 1
        p.merge(b_sum, b_count, b_min, b_max, b_last, b_hist)

    # -- eviction (bounded cardinality) ---------------------------------

    def _evict_one_locked(self) -> None:
        """Emit the oldest open partial early — a split rollup, counted
        and alarmed, never a loss."""
        slot = min(self._windows)
        d = self._windows[slot]
        key, p = next(iter(d.items()))
        del d[key]
        if not d:
            del self._windows[slot]
        self._n_keys -= 1
        self._m_evicted.add(1)
        self._pending_evicted.append((slot * self.slide_s,
                                      slot * self.slide_s + self.window_s,
                                      key, p))
        if not self._evict_alarmed:
            self._evict_alarmed = True
            from ..monitor.alarms import (AlarmLevel, AlarmManager,
                                          AlarmType)
            AlarmManager.instance().send_alarm(
                AlarmType.AGG_WINDOW_EVICTION,
                f"rollup key cardinality hit MaxKeys={self.max_keys}: "
                "open partials are being emitted early (split rollups)",
                AlarmLevel.WARNING, pipeline=self._pipeline_name)

    # -- window close ---------------------------------------------------

    def _flush_gate(self) -> bool:
        try:
            chaos.faultpoint(POINT_AGG_FLUSH)
        except ChaosFault:
            self._m_flush_faults.add(1)
            log.warning("aggregator.flush fault injected: deferring "
                        "window close (windows stay open)")
            return False
        return True

    def _close_ready_locked(self) -> List[PipelineEventGroup]:
        """Emit every window whose end the watermark passed, plus any
        partials evicted during this call."""
        rows: List[Tuple[int, int, Tuple, _Partial]] = []
        if self._pending_evicted:
            rows.extend(self._pending_evicted)
            self._pending_evicted = []
        if self._max_ts is not None and self._windows:
            wm = self._max_ts - self.lateness_s
            per_slot = self.window_s // self.slide_s
            # first window start the watermark has NOT yet closed:
            # w0 closes iff w0*S + W <= wm
            first_open = (wm - self.window_s) // self.slide_s + 1
            if self._next_close is None:
                # cold start: the earliest window containing any open
                # slot (sliding windows emit partially filled)
                self._next_close = min(self._windows) - per_slot + 1
            while self._windows and self._next_close < first_open:
                # fast-forward over stretches with no open slots in one
                # step — but never past the watermark horizon, or rows
                # inside the lateness allowance after an event-time gap
                # would be spuriously declared late
                earliest = min(self._windows) - per_slot + 1
                if earliest > self._next_close:
                    self._next_close = min(earliest, first_open)
                    continue
                rows.extend(self._emit_window_locked(self._next_close))
                self._next_close += 1
        if not rows:
            return []
        return [self._build_group(rows)]

    def _emit_window_locked(self, w0: int
                            ) -> List[Tuple[int, int, Tuple, _Partial]]:
        """Merge the slots covering window starting at slot w0 and retire
        slot w0 (the oldest slot no future window needs)."""
        per_slot = self.window_s // self.slide_s
        merged: Dict[Tuple, _Partial] = {}
        for s in range(w0, w0 + per_slot):
            d = self._windows.get(s)
            if not d:
                continue
            for key, p in d.items():
                m = merged.get(key)
                if m is None:
                    m = merged[key] = _Partial(
                        sr.N_HIST if self.emit_histogram else 0)
                m.merge_partial(p)
        d = self._windows.pop(w0, None)
        if d:
            self._n_keys -= len(d)
        start = w0 * self.slide_s
        end = start + self.window_s
        return [(start, end, key, p) for key, p in merged.items()]

    # -- emission -------------------------------------------------------

    _AGG_FIELDS = ("window_start", "window_end", "sum", "count", "min",
                   "max", "last")

    @staticmethod
    def _fmt(v: float) -> bytes:
        # repr() is the shortest round-trip spelling — identical on the
        # columnar and dict paths because both format the same f64.
        # Non-finite first: the value grammar admits "inf", and inf+-inf
        # inside one key makes sum NaN — int(v) would raise AFTER the
        # window state was popped, losing the whole close
        if v != v:
            return b"nan"
        if v == float("inf"):
            return b"inf"
        if v == float("-inf"):
            return b"-inf"
        if v == int(v) and abs(v) < 1e16:
            return b"%d" % int(v)
        return repr(v).encode()

    def _build_group(self, rows: List[Tuple[int, int, Tuple, _Partial]]
                     ) -> PipelineEventGroup:
        """Closed-window rollup rows as ONE columnar group over a fresh
        arena — field span columns only, riding every zero-copy
        serializer downstream.  The metric-name column always emits
        under the CANONICAL ``__name__`` (MetricNameKey configures the
        INPUT column; downstream consumers — the prometheus flusher —
        must not have to know it).  Rows arriving split (an eviction
        followed by the same window's normal close) coalesce back into
        one row per (window, key) so a single payload never carries two
        same-timestamp samples of one series."""
        merged: Dict[Tuple, _Partial] = {}
        order: List[Tuple] = []
        for start, end, key, p in rows:
            mk = (start, end, key)
            m = merged.get(mk)
            if m is None:
                merged[mk] = p
                order.append(mk)
            else:
                m.merge_partial(p)
        rows = [(mk[0], mk[1], mk[2], merged[mk]) for mk in order]
        field_names = (["__name__"] + self.label_keys
                       + list(self._AGG_FIELDS)
                       + (["hist"] if self.emit_histogram else []))
        F = len(field_names)
        M = len(rows)
        blob = bytearray()
        offs = np.zeros((M, F), dtype=np.int32)
        lens = np.full((M, F), -1, dtype=np.int32)
        timestamps = np.zeros(M, dtype=np.int64)
        row_off = np.zeros(M, dtype=np.int32)
        row_len = np.zeros(M, dtype=np.int32)

        def put(i, f, data) -> None:
            if data is None:
                return
            offs[i, f] = len(blob)
            lens[i, f] = len(data)
            blob.extend(data)

        for i, (start, end, key, p) in enumerate(rows):
            row_off[i] = len(blob)
            timestamps[i] = end
            for k, kb in enumerate(key):
                put(i, k, kb)
            base = len(key)
            put(i, base + 0, b"%d" % start)
            put(i, base + 1, b"%d" % end)
            put(i, base + 2, self._fmt(p.sum))
            put(i, base + 3, b"%d" % p.count)
            put(i, base + 4, self._fmt(p.min))
            put(i, base + 5, self._fmt(p.max))
            put(i, base + 6, self._fmt(p.last))
            if self.emit_histogram:
                nz = np.nonzero(p.hist)[0]
                put(i, base + 7, b",".join(
                    b"%d:%d" % (int(b), int(p.hist[b])) for b in nz))
            row_len[i] = len(blob) - row_off[i]
        sb = SourceBuffer(max(len(blob), 16))
        off0 = sb.allocate(len(blob))
        sb.write_at(off0, bytes(blob))
        if off0:
            offs += off0
            row_off += off0
        cols = ColumnarLogs(row_off, row_len, timestamps)
        cols.content_consumed = True
        cols.set_fields_matrix(field_names, offs, lens)
        out = PipelineEventGroup(sb)
        out.set_columns(cols)
        out.set_tag(b"__rollup__", self.name.encode())
        self._m_emitted.add(M)
        self._ledger_rows(ledger.B_AGG_EMIT, M, len(blob))
        return out

    # -- gauges ---------------------------------------------------------

    def _export_gauges_locked(self) -> None:
        self._g_open_keys.set(float(self._n_keys))
        self._g_open_windows.set(float(len(self._windows)))
        if self._windows and self._max_ts is not None:
            lag = self._max_ts - min(self._windows) * self.slide_s
            self._g_lag.set(float(max(lag, 0)))
        else:
            self._g_lag.set(0.0)

    # -- timeout / drain ------------------------------------------------

    def flush_timeout(self) -> List[PipelineEventGroup]:
        """TimeoutFlushManager cadence: close what the watermark allows;
        when the event-time watermark has stalled for IdleFlushSecs of
        wall-clock (idle source), force-close everything."""
        if not self._flush_gate():
            return []
        with self._lock:
            out = self._close_ready_locked()
            if self._windows and self._last_event_wall and \
                    time.monotonic() - self._last_event_wall \
                    >= self.idle_flush_s:
                self._m_idle_flush.add(1)
                out.extend(self._force_flush_locked())
            self._export_gauges_locked()
        return out

    def flush(self) -> List[PipelineEventGroup]:
        """Pipeline drain: force-close every open window.  The chaos
        point is consumed non-raising here — drain MUST flush (the
        enable_full_drain_mode contract the storm test asserts)."""
        dec = chaos.faultpoint(POINT_AGG_FLUSH, raise_=False)
        if dec is not None:
            self._m_flush_faults.add(1)
        with self._lock:
            out = self._force_flush_locked()
            self._export_gauges_locked()
        return out

    def _force_flush_locked(self) -> List[PipelineEventGroup]:
        rows: List[Tuple[int, int, Tuple, _Partial]] = []
        if self._pending_evicted:
            rows.extend(self._pending_evicted)
            self._pending_evicted = []
        while self._windows:
            if self._next_close is None or \
                    self._next_close < min(self._windows) - \
                    (self.window_s // self.slide_s) + 1:
                self._next_close = min(self._windows) - \
                    (self.window_s // self.slide_s) + 1
            rows.extend(self._emit_window_locked(self._next_close))
            self._next_close += 1
        if not rows:
            return []
        return [self._build_group(rows)]
