#!/usr/bin/env python
"""Back-compat shim: this gate moved to scripts/resident_equivalence.py.

The old name collided (one edit distance) with scripts/fuse_equivalence.py
— the fused-DFA gate — and the two kept being mistaken for duplicates.
The rename spells out what each one checks:

  * resident_equivalence.py — loongresident: fused PIPELINE programs
    (stage fusion on vs off must be byte-identical);
  * fuse_equivalence.py     — loongfuse: fused multi-accept DFA vs
    per-pattern `re` classification.

This shim keeps old invocations working; new callers should use
scripts/resident_equivalence.py directly.
"""

import runpy
import os

runpy.run_path(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "resident_equivalence.py"),
               run_name="__main__")
