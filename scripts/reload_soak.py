#!/usr/bin/env python
"""loongtenant reload soak: sustained config churn under sustained ingest.

Builds the real in-process stack (process queues → sharded ProcessorRunner
→ CollectionPipelineManager pipelines → flusher_checker sinks) with the
conservation ledger + live auditor ON, then:

  * N tenant pipelines ingest continuously (per-tenant per-source
    sequence-stamped rows);
  * a churn loop hot-reloads tenants round-robin at ``--rate`` reloads/sec
    for ``--seconds`` (optionally add/remove a rotating extra tenant with
    ``--churn-topology``, and storm the control plane with
    ``--chaos-seed``);
  * at the end the stack quiesces and the script FAILS (exit 1) if ANY of:
      - a tenant's conservation residual is nonzero at quiesce,
      - the live auditor raised CONSERVATION_RESIDUAL mid-soak,
      - ledger send_ok != pushed for any tenant (an unledgered loss a
        residual of 0 could in principle mask),
      - a CONFIG_UPDATE_FAILED alarm fired without --chaos-seed (reloads
        of a valid config must never fail),
      - any reload latency was never recorded.

Wired into scripts/lint.sh as a short smoke and into scripts/soak.sh /
the ``-m slow`` tier with longer parameters (docs/robustness.md).

    python scripts/reload_soak.py --tenants 4 --rate 5 --seconds 3
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _cfg():
    return {
        "inputs": [{"Type": "input_static_file_onetime",
                    "FilePaths": ["/nonexistent"]}],
        "global": {"ProcessQueueCapacity": 64},
        "processors": [{"Type": "processor_parse_regex_tpu",
                        "Regex": r"(\w+):(\d+)", "Keys": ["src", "seq"]}],
        "flushers": [{"Type": "flusher_checker"}],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--rate", type=float, default=5.0,
                    help="hot reloads per second across the tenant set")
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--threads", type=int, default=2)
    ap.add_argument("--chaos-seed", type=int, default=None,
                    help="storm pipeline_manager.update while churning")
    ap.add_argument("--churn-topology", action="store_true",
                    help="also add/remove a rotating extra tenant")
    args = ap.parse_args()

    from loongcollector_tpu import chaos
    from loongcollector_tpu.chaos import ChaosPlan, FaultSpec
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    from loongcollector_tpu.monitor import ledger
    from loongcollector_tpu.monitor.alarms import AlarmManager, AlarmType
    from loongcollector_tpu.pipeline.pipeline_manager import (
        CollectionPipelineManager, ConfigDiff)
    from loongcollector_tpu.pipeline.queue.process_queue_manager import \
        ProcessQueueManager
    from loongcollector_tpu.pipeline.queue.sender_queue import \
        SenderQueueManager
    from loongcollector_tpu.runner.processor_runner import ProcessorRunner

    tenants = [f"soak{i:03d}" for i in range(args.tenants)]
    failures = []

    ledger.enable()
    ledger.reset()
    auditor = ledger.start_auditor(interval_s=0.05)
    pqm = ProcessQueueManager()
    sqm = SenderQueueManager()
    mgr = CollectionPipelineManager(pqm, sqm)
    runner = ProcessorRunner(pqm, mgr, thread_count=args.threads)
    runner.init()

    def apply(added=None, modified=None, removed=()):
        d = ConfigDiff()
        d.added.update(added or {})
        d.modified.update(modified or {})
        d.removed.extend(removed)
        mgr.update_pipelines(d)

    apply(added={t: _cfg() for t in tenants})
    for t in tenants:
        if mgr.find_pipeline(t) is None:
            print(f"FATAL: tenant {t} never initialised", file=sys.stderr)
            return 1

    if args.chaos_seed is not None:
        chaos.install(ChaosPlan(args.chaos_seed, {
            "pipeline_manager.update": FaultSpec(
                prob=0.3, kinds=(chaos.ACTION_ERROR, chaos.ACTION_DELAY),
                delay_range=(0.001, 0.01), max_faults=16)}))

    pushed = {t: 0 for t in tenants}
    seqs = {}
    stop = threading.Event()

    def push_one(tenant: str, src: bytes, rows: int = 4) -> None:
        p = mgr.find_pipeline(tenant)
        if p is None:          # mid-remove window (--churn-topology)
            return
        key = (tenant, src)
        s0 = seqs.get(key, 0)
        payload = b"\n".join(b"%s:%d" % (src, s0 + j)
                             for j in range(rows)) + b"\n"
        sb = SourceBuffer(len(payload) + 64)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(sb.copy_string(payload))
        g.set_tag(b"__source__", src)
        deadline = time.monotonic() + 30
        while not pqm.push_queue(p.process_queue_key, g):
            if time.monotonic() > deadline or stop.is_set():
                return
            time.sleep(0.002)
        seqs[key] = s0 + rows
        pushed[tenant] += rows

    def pusher():
        i = 0
        while not stop.is_set():
            push_one(tenants[i % len(tenants)],
                     b"s%d" % (i % 3))
            i += 1
            time.sleep(0.002)

    push_thread = threading.Thread(target=pusher, daemon=True)
    push_thread.start()

    reloads = 0
    extra_serial = 0
    interval = 1.0 / max(args.rate, 0.1)
    t_end = time.monotonic() + args.seconds
    try:
        while time.monotonic() < t_end:
            target = tenants[reloads % len(tenants)]
            apply(modified={target: _cfg()})
            reloads += 1
            if args.churn_topology and reloads % 7 == 0:
                name = f"extra{extra_serial:03d}"
                if mgr.find_pipeline(name) is None:
                    apply(added={name: _cfg()})
                else:
                    apply(removed=[name])
                    extra_serial += 1
            time.sleep(interval)
    finally:
        stop.set()
        push_thread.join(timeout=30)
        chaos.uninstall()

    snap = ledger.wait_quiesced(timeout=60)
    if snap is None:
        failures.append(f"ledger never quiesced "
                        f"(live_inflight={ledger.live_inflight()})")
        snap = ledger.active_ledger().snapshot()
    residuals = ledger.residuals(snap)
    for t, res in sorted(residuals.items()):
        if res != 0:
            failures.append(f"tenant {t}: residual {res:+d} at quiesce")
    led = ledger.active_ledger()
    for t, want in sorted(pushed.items()):
        got = led.total(t, ledger.B_SEND_OK)
        if got != want:
            failures.append(f"tenant {t}: pushed {want} but send_ok {got}")
    if auditor.residual_alarms_total:
        failures.append(
            f"auditor raised {auditor.residual_alarms_total} "
            "CONSERVATION_RESIDUAL alarm(s) mid-soak")
    alarms = AlarmManager.instance().flush()
    bad_types = {AlarmType.CONSERVATION_RESIDUAL.value}
    if args.chaos_seed is None:
        bad_types.add(AlarmType.CONFIG_UPDATE_FAILED.value)
    for a in alarms:
        if a["alarm_type"] in bad_types:
            failures.append(f"alarm {a['alarm_type']}: "
                            f"{a['alarm_message']} x{a['alarm_count']}")
    from loongcollector_tpu.pipeline.pipeline_manager import \
        reload_histogram
    hist = reload_histogram().snapshot()
    if hist["count"] < reloads * 0.5 and args.chaos_seed is None:
        failures.append(
            f"only {hist['count']} reload latencies recorded for "
            f"{reloads} reloads")

    runner.stop()
    mgr.stop_all()
    ledger.stop_auditor()

    report = {
        "tenants": args.tenants,
        "reloads": reloads,
        "events_pushed": sum(pushed.values()),
        "send_ok": sum(led.total(t, ledger.B_SEND_OK) for t in pushed),
        "reload_ms_p50": round(hist["p50"] * 1000.0, 3),
        "reload_ms_p99": round(hist["p99"] * 1000.0, 3),
        "residual_alarms": auditor.residual_alarms_total,
        "failures": failures,
    }
    print(json.dumps(report, sort_keys=True))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print(f"reload soak OK: {reloads} reloads over {args.tenants} tenants, "
          f"{report['events_pushed']} events conserved", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
