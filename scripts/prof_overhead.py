#!/usr/bin/env python
"""loongprof overhead smoke gate (wired into scripts/lint.sh).

The loongprof contract (docs/observability.md) mirrors loongtrace's: with
``LOONG_PROF`` off, every hook — ``prof.is_active``, ``prof.push_marker``,
``prof.pop_marker`` — is one module-global read + branch.  Same two-layer
proof as scripts/trace_overhead.py, same paired-min method:

1. **Per-hook microbench** — ns/call of the disabled hooks under a
   generous absolute ceiling (a disabled path that allocates or locks
   blows through it immediately).

2. **10k-event synthetic pipeline** — the marker-instrumented path
   (ProcessorInstance split stage + SLS serialization) timed with hooks
   as shipped (profiler disabled) vs the same hooks monkeypatched to
   bare no-ops, interleaved paired rounds; the gate is the MINIMUM
   paired disabled/baseline ratio (>5% in EVERY round fails).  The
   profiler-enabled time is reported informationally — enabling MAY
   cost, disabling MUST NOT.
"""

import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__file__), ".."))

N_EVENTS = 10_000
REPEATS = 9
MAX_DISABLED_OVER_BASELINE = 1.05      # the 5% gate
MAX_HOOK_NS = 2_000                    # catastrophic-regression ceiling


def bench_hooks():
    from loongcollector_tpu import prof
    prof.disable()
    out = {}
    for label, fn in (("is_active", prof.is_active),
                      ("push_marker", lambda: prof.push_marker("p", "x")),
                      ("pop_marker", prof.pop_marker)):
        n = 200_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        out[label] = best * 1e9
    return out


def make_runner():
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    from loongcollector_tpu.pipeline.plugin.instance import ProcessorInstance
    from loongcollector_tpu.pipeline.plugin.interface import PluginContext
    from loongcollector_tpu.pipeline.serializer.sls_serializer import \
        SLSEventGroupSerializer
    from loongcollector_tpu.processor.split_log_string import \
        ProcessorSplitLogString
    inst = ProcessorInstance(ProcessorSplitLogString(), "split/prof_overhead")
    assert inst.init({}, PluginContext("prof_overhead"))
    ser = SLSEventGroupSerializer()
    line = b"2024-01-02 03:04:05 INFO request handled ok\n"
    data = line * N_EVENTS

    def run_timed():
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(sb.copy_string(data))
        t0 = time.perf_counter()
        inst.process([g])
        ser.serialize([g])
        dt = time.perf_counter() - t0
        assert len(g) == N_EVENTS
        return dt

    return inst, run_timed


def main() -> int:
    from loongcollector_tpu import prof
    hooks = bench_hooks()
    print("disabled hook cost (ns/call): "
          + ", ".join(f"{k}={v:.0f}" for k, v in hooks.items()))
    bad = {k: v for k, v in hooks.items() if v > MAX_HOOK_NS}
    if bad:
        print(f"FAIL: disabled hooks over {MAX_HOOK_NS} ns: {bad}")
        return 1

    import gc
    inst, run_timed = make_runner()
    noop_active = lambda: False                       # noqa: E731
    noop_none = lambda *a, **k: None                  # noqa: E731
    real = (prof.is_active, prof.push_marker, prof.pop_marker,
            prof.active_profiler)

    def set_baseline():
        prof.disable()
        prof.is_active = noop_active
        prof.push_marker = noop_none
        prof.pop_marker = noop_none
        prof.active_profiler = noop_none

    def set_disabled():
        (prof.is_active, prof.push_marker, prof.pop_marker,
         prof.active_profiler) = real
        prof.disable()

    def set_enabled():
        (prof.is_active, prof.push_marker, prof.pop_marker,
         prof.active_profiler) = real
        # sampler runs for real — the enabled number includes the
        # sampling thread stealing cycles, as production would
        prof.enable(hz=97)

    # Paired rounds, min ratio across rounds: a REAL disabled-path
    # regression is systematic and survives every pairing; co-tenant CPU
    # steal on a shared core does not (see scripts/trace_overhead.py).
    dis_ratios, en_ratios = [], []
    try:
        run_timed()                                   # warm the path
        for i in range(REPEATS):
            pair = [("baseline", set_baseline), ("disabled", set_disabled)]
            if i % 2:                                 # kill position bias
                pair.reverse()
            times = {}
            for name, setup in pair + [("enabled", set_enabled)]:
                setup()
                gc.collect()
                times[name] = run_timed()
                prof.disable()
            dis_ratios.append(times["disabled"] / times["baseline"])
            en_ratios.append(times["enabled"] / times["baseline"])
    finally:
        (prof.is_active, prof.push_marker, prof.pop_marker,
         prof.active_profiler) = real
        prof.disable()
        inst.metrics.mark_deleted()

    ratio = min(dis_ratios)
    print(f"{N_EVENTS}-event synthetic pipeline, {REPEATS} paired rounds: "
          f"disabled/baseline min={ratio:.3f} "
          f"median={sorted(dis_ratios)[len(dis_ratios) // 2]:.3f}  "
          f"enabled/baseline min={min(en_ratios):.3f}")
    if ratio > MAX_DISABLED_OVER_BASELINE:
        print(f"FAIL: disabled-path overhead {(ratio - 1) * 100:.1f}% "
              f"> {(MAX_DISABLED_OVER_BASELINE - 1) * 100:.0f}% in every "
              "round — the disabled profiler must stay one branch per hook")
        return 1
    print("prof overhead OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
