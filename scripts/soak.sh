#!/usr/bin/env bash
# Full chaos soak — the long-running sibling of scripts/lint.sh
# (docs/robustness.md).  Tier-1 already runs the fast fixed-seed subset of
# tests/test_chaos_soak.py; this script adds the extended seed matrix
# (`-m slow`) plus a loonglint pass so a soak run reports on both the
# dynamic and static robustness gates.
#
#   scripts/soak.sh                 # full soak, default seeds
#   LOONG_CHAOS_SEED=123 scripts/soak.sh --reproduce
#       # re-run ONLY the tier-1 storm matrix under one env-driven seed
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" == "--reproduce" ]]; then
    seed="${LOONG_CHAOS_SEED:?--reproduce needs LOONG_CHAOS_SEED set}"
    echo "== reproducing storm seed ${seed} =="
    JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_chaos_soak.py::TestSinkStorm" \
        "tests/test_chaos_soak.py::TestDeviceStorm" \
        -q -p no:cacheprovider -k "[${seed}]"
    exit 0
fi

echo "== loonglint =="
python -m loongcollector_tpu.analysis

echo "== chaos soak: tier-1 seed matrix =="
JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_soak.py \
    -q -m 'not slow' -p no:cacheprovider

echo "== chaos soak: sharded-plane storm matrix (loongshard) =="
# the multi-worker storms: 8 seeds through thread_count=4 shards — zero
# loss, inflight==0, per-source order, schedule prefix-determinism
JAX_PLATFORMS=cpu LOONG_PROCESS_THREADS=4 python -m pytest \
    tests/test_loongshard.py -q -m 'not slow' -p no:cacheprovider

echo "== chaos soak: extended seed matrix (slow) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_soak.py \
    -q -m slow -p no:cacheprovider

echo "== slo burn-rate storm: one seeded breaker-open episode (loongslo) =="
# a seeded http_sink.send storm with the freshness SLO plane live: exactly
# one SLO_BURN_RATE alarm per episode, sink hop dominant in the budget
# breakdown, alert clears after the breaker re-closes (full 8-seed matrix
# runs in tier-1 via tests/test_loongslo.py)
JAX_PLATFORMS=cpu python -m pytest \
    "tests/test_loongslo.py::TestSinkStormSLO" \
    -q -p no:cacheprovider -k "[42]"

echo "== crash storm: 8-seed SIGKILL matrix (loongcrash) =="
# kill the real agent at every seeded pipeline boundary (ingest, queue
# push, send, spill), restart, drain: sink ⊇ corpus byte-for-byte with
# duplicates bounded by the unacked window and post-restart ledger
# residual 0 (docs/robustness.md "Crash durability")
JAX_PLATFORMS=cpu python scripts/crash_storm.py --lines 160

echo "== native sanitizer soak (TSan) =="
# the long-running home of the opt-in TSan variant: data races in the
# native plane surface under the soak's time budget, not lint's
# (scripts/sanitize.sh --tsan; probe-gated like the lint-side ASan pass)
if scripts/sanitize.sh --probe >/dev/null 2>&1; then
    scripts/sanitize.sh --tsan
    scripts/sanitize.sh
else
    echo "no sanitizer toolchain; skipped (scripts/sanitize.sh --probe)"
fi

echo "== reload soak: sustained config churn (loongtenant) =="
# long churn with topology add/remove AND a control-plane chaos storm —
# zero residual per tenant, send_ok == pushed, across hundreds of reloads
JAX_PLATFORMS=cpu python scripts/reload_soak.py \
    --tenants 8 --rate 10 --seconds 30 --churn-topology
JAX_PLATFORMS=cpu python scripts/reload_soak.py \
    --tenants 8 --rate 10 --seconds 30 --churn-topology --chaos-seed 1337

echo "soak OK"
