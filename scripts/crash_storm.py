#!/usr/bin/env python
"""loongcrash storm: SIGKILL the REAL agent at a seeded fault point, restart
it, and prove the at-least-once contract end to end.

One seed = one kill site.  The harness

  1. pre-writes a corpus file (fully written before the agent starts, so
     reader chunk boundaries are deterministic across the original run and
     the post-crash re-read — exact-span crc dedup applies);
  2. boots `python -m loongcollector_tpu.application --cpu` with
     ``LOONG_CHAOS_CRASH=<point>:<nth>`` armed — the chaos plane SIGKILLs
     the process at the nth hit of that point (process.crash family), with
     THIS harness process as the HTTP sink, so the exact set of lines
     delivered before the kill is known to the assertion, not sampled;
  3. restarts the agent clean (same data dir), waits until the sink holds
     every corpus line, SIGTERM-drains it;
  4. asserts: unique sink lines == corpus byte-for-byte (zero loss),
     duplicates bounded by the unacked window (lines the first run
     delivered + events it had spilled durably), the restarted agent's
     /debug/status reports the unclean shutdown + its replay-duplicate
     counters, and the post-restart ledger reconciles to residual 0.

Seeds map deterministically onto (point, nth) pairs across the
ingest/process/send/spill boundaries — `scripts/soak.sh` runs the 8-seed
matrix, `scripts/lint.sh` runs seed 3 as a smoke.

Usage:  python scripts/crash_storm.py [--seed N] [--lines N] [--json PATH]
"""

import argparse
import http.server
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the 8-seed matrix: kill at the nth hit of each pipeline boundary.
# file_input.read = ingest, bounded_queue.push = process handoff,
# http_sink.send = the send path (pre-POST, so the in-flight payload is
# unacked), disk_buffer.write = mid-spill.  disk_buffer.write may never
# fire on a healthy run — the harness then kills AFTER full delivery,
# which exercises the ack-to-checkpoint-dump window instead.
SEED_MATRIX = [
    ("file_input.read", 1),
    ("file_input.read", 4),
    ("bounded_queue.push", 2),
    ("http_sink.send", 0),
    ("http_sink.send", 2),
    ("http_sink.send", 6),
    ("disk_buffer.write", 0),
    ("bounded_queue.push", 7),
]


class _Sink(http.server.ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr):
        super().__init__(addr, _SinkHandler)
        self.lines = []          # (phase, content) in arrival order
        self.phase = 1
        self.lock = threading.Lock()


class _SinkHandler(http.server.BaseHTTPRequestHandler):
    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        rows = []
        for line in body.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line).get("content", ""))
            except ValueError:
                rows.append(line)
        with self.server.lock:
            phase = self.server.phase
            for r in rows:
                self.server.lines.append((phase, r))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, *a):      # noqa: D102 - silence request spam
        pass


def _spawn(conf, data, extra_env):
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "loongcollector_tpu.application", "--cpu",
         "--config", conf, "--data-dir", data],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    # drain stdout continuously: a full pipe buffer would BLOCK the agent's
    # logging mid-drive, and the retained lines carry the ephemeral
    # exposition port + the post-mortem for convergence failures
    lines = []

    def _drain():
        for raw in proc.stdout:
            lines.append(raw)
    threading.Thread(target=_drain, daemon=True).start()
    proc.log_lines = lines
    return proc


_EXPO_RE = re.compile(rb"exposition endpoint on http://127\.0\.0\.1:(\d+)/")


def _expo_port(proc, timeout=30):
    """The agent binds LOONG_EXPO_PORT=0 to an ephemeral port (a
    pre-probed 'free' port is a TOCTOU race against every other test on
    the host) and logs it — parse it out of the drained log."""
    found = []

    def _probe():
        for raw in list(proc.log_lines):
            m = _EXPO_RE.search(raw)
            if m:
                found.append(int(m.group(1)))
                return True
        return proc.poll() is not None
    _wait(_probe, timeout=timeout)
    return found[0] if found else None


def _wait(cond, timeout, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _scrape_status(port, timeout=15):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/status", timeout=3) as r:
                return json.loads(r.read())
        except (OSError, ValueError):
            time.sleep(0.5)
    return None


def run_storm(seed, n_lines=160, workdir=None, verbose=False,
              dump_interval=1):
    """One seeded kill-restart-drain cycle; returns the result dict and
    raises AssertionError on any contract violation."""
    import tempfile
    point, nth = SEED_MATRIX[seed % len(SEED_MATRIX)]
    tmp = workdir or tempfile.mkdtemp(prefix=f"crash_storm_s{seed}_")
    conf = os.path.join(tmp, "conf")
    data = os.path.join(tmp, "data")
    logs = os.path.join(tmp, "logs")
    for d in (conf, data, logs):
        os.makedirs(d, exist_ok=True)

    sink = _Sink(("127.0.0.1", 0))
    sink_port = sink.server_address[1]
    threading.Thread(target=sink.serve_forever, daemon=True).start()

    corpus = [f"s{seed}-{i:05d}-" + "x" * (17 + (i * 7 + seed) % 41)
              for i in range(n_lines)]
    logf = os.path.join(logs, "app.log")
    with open(logf, "w") as f:            # fully pre-written: deterministic
        f.write("\n".join(corpus) + "\n")  # chunk boundaries across re-reads

    with open(os.path.join(conf, "storm.json"), "w") as f:
        json.dump({
            "inputs": [{"Type": "input_file", "FilePaths": [logf],
                        "TailExisted": True}],
            "flushers": [{"Type": "flusher_http",
                          "RemoteURL":
                          f"http://127.0.0.1:{sink_port}/ingest"}],
        }, f)
    # a short checkpoint cadence keeps the crash window realistic; the
    # watermark (not the dump clock) is what durability rides on
    with open(os.path.join(data, "loongcollector_config.json"), "w") as f:
        json.dump({"checkpoint_dump_interval": dump_interval}, f)

    t0 = time.monotonic()
    # ---- phase 1: armed run — SIGKILL at the nth hit of `point` ----------
    proc = _spawn(conf, data, {"LOONG_CHAOS_CRASH": f"{point}:{nth}",
                               "LOONG_LEDGER": "1"})
    _wait(lambda: proc.poll() is not None or len(sink.lines) >= n_lines,
          timeout=60)
    if proc.poll() is None:
        # the armed point never reached hit nth (e.g. no spill happened on
        # a healthy run): give the late hit a moment, then kill by hand
        # AFTER delivery — the ack-to-checkpoint-dump window
        _wait(lambda: proc.poll() is not None, timeout=2)
    crash_fired = proc.poll() is not None
    if not crash_fired:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    with sink.lock:
        phase1_lines = [c for _, c in sink.lines]
        sink.phase = 2
    if verbose:
        print(f"  phase1: crash_fired={crash_fired} rc={proc.returncode} "
              f"delivered={len(phase1_lines)}")
    assert proc.returncode == -signal.SIGKILL, \
        f"agent exited {proc.returncode}, expected SIGKILL"
    marker = os.path.join(data, "unclean.marker")
    assert os.path.exists(marker), "crash marker missing after SIGKILL"

    # durably spilled events at the kill: part of the duplicate bound
    buffered = 0
    bufdir = os.path.join(data, "buffer")
    if os.path.isdir(bufdir):
        for root, _dirs, files in os.walk(bufdir):
            for name in files:
                if name.endswith(".lcb"):
                    try:
                        with open(os.path.join(root, name), "rb") as f:
                            buffered += int(json.loads(
                                f.readline().decode()).get("event_cnt", 0))
                    except (OSError, ValueError):
                        pass

    # ---- phase 2: clean restart — recover, re-read, drain ----------------
    proc = _spawn(conf, data, {"LOONG_EXPO_PORT": "0",
                               "LOONG_LEDGER": "1"})
    status = {}
    try:
        ok = _wait(lambda: len({c for _, c in sink.lines}) >= n_lines,
                   timeout=90)
        if not ok:
            out = b"".join(proc.log_lines)
            raise AssertionError(
                f"seed {seed} ({point}:{nth}): sink never converged — "
                f"{len({c for _, c in sink.lines})}/{n_lines} unique lines; "
                + out.decode(errors="replace")[-1500:])
        # quiesce: no new arrivals for a full second, then scrape + drain
        def _settled():
            n = len(sink.lines)
            time.sleep(1.0)
            return len(sink.lines) == n
        _wait(_settled, timeout=20, interval=0)
        expo_port = _expo_port(proc)
        status = (_scrape_status(expo_port)
                  if expo_port is not None else None) or {}
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
    wall = time.monotonic() - t0

    # ---- assertions ------------------------------------------------------
    with sink.lock:
        all_lines = [c for _, c in sink.lines]
    unique = set(all_lines)
    missing = set(corpus) - unique
    foreign = unique - set(corpus)
    assert not missing, \
        f"seed {seed} ({point}:{nth}): LOST {len(missing)} lines, " \
        f"e.g. {sorted(missing)[:3]}"
    assert not foreign, \
        f"seed {seed} ({point}:{nth}): corrupt/foreign lines {list(foreign)[:3]}"
    duplicates = len(all_lines) - len(unique)
    window = len(phase1_lines) + buffered
    assert duplicates <= max(window, 1), \
        f"seed {seed} ({point}:{nth}): {duplicates} duplicates exceed the " \
        f"unacked window ({len(phase1_lines)} delivered + {buffered} spilled)"

    rec = status.get("recovery", {})
    assert rec.get("unclean_shutdown") is True, \
        f"seed {seed}: restart did not report unclean_shutdown: {rec}"
    suppressed = int(rec.get("replay_duplicate_events", 0))
    # every re-read of an already-delivered span is either suppressed
    # (counted by the recovery window) or delivered as one of the bounded
    # duplicates — nothing falls through uncounted
    assert suppressed + duplicates <= window + len(corpus), \
        f"seed {seed}: replay accounting off: suppressed={suppressed} " \
        f"delivered_dup={duplicates} window={window}"

    residuals = (status.get("ledger") or {}).get("residuals") or {}
    bad = {k: v for k, v in residuals.items() if v != 0}
    assert not bad, \
        f"seed {seed} ({point}:{nth}): post-restart ledger residuals {bad}"

    sink.shutdown()
    return {
        "seed": seed, "point": point, "nth": nth,
        "crash_fired": crash_fired,
        "corpus_lines": len(corpus),
        "phase1_delivered": len(phase1_lines),
        "buffered_at_kill": buffered,
        "duplicates_delivered": duplicates,
        "replay_duplicate_events": suppressed,
        "unclean_shutdown_total": int(rec.get("unclean_shutdown_total", 0)),
        "recovered_events_total": int(rec.get("recovered_events_total", 0)),
        "recovery_wall_s": float(rec.get("recovery_wall_s", 0.0)),
        "wall_s": round(wall, 3),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=None,
                    help="single seed (default: full 8-seed matrix)")
    ap.add_argument("--lines", type=int, default=160)
    ap.add_argument("--json", default="",
                    help="write per-seed result records to this file")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    seeds = [args.seed] if args.seed is not None else list(
        range(len(SEED_MATRIX)))
    results = []
    for seed in seeds:
        point, nth = SEED_MATRIX[seed % len(SEED_MATRIX)]
        print(f"== crash storm seed {seed}: SIGKILL at {point} hit {nth} ==")
        res = run_storm(seed, n_lines=args.lines, verbose=args.verbose)
        results.append(res)
        print(f"   zero loss; {res['duplicates_delivered']} dup delivered, "
              f"{res['replay_duplicate_events']} suppressed, "
              f"{res['wall_s']}s")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    print(f"crash storm OK ({len(results)} seed(s))")


if __name__ == "__main__":
    main()
