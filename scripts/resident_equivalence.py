#!/usr/bin/env python
"""loongresident equivalence gate (scripts/lint.sh + tier-1).

The fused pipeline program must be a pure execution-plan change: for
every pipeline family — regex, regex+grok, delimiter, json, multiline —
running the SAME processor chain with stage fusion forced on
(``LOONG_FUSED=1``: one fused device program per batch slot) and forced
off (``LOONG_FUSED=0``: the per-stage dispatch path, which on this host
routes through the native/host tiers) must produce BYTE-IDENTICAL
groups: same surviving rows, same field spans, same kept/renamed
sources, same parse_ok vector.  Identity is compared as a blake2b digest
over the canonical column snapshot.

Families where fusion engages (a planned run of ≥ 2 stages exists) also
assert that the fused side really did fuse — one device dispatch for the
run — so the gate cannot rot into comparing the staged path to itself.
The json family intentionally has NO fusable run (parse_json's span
emission is native-plane): there the gate pins that fusion leaves the
pipeline untouched.

Exit 0 = identical everywhere; exit 1 = any digest mismatch (printed
per family).
"""

from __future__ import annotations

import hashlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from loongcollector_tpu import models  # noqa: E402
from loongcollector_tpu.models import (ColumnarLogs,  # noqa: E402
                                       PipelineEventGroup, SourceBuffer)
from loongcollector_tpu.ops import fused_pipeline as fp  # noqa: E402
from loongcollector_tpu.ops.device_plane import DevicePlane  # noqa: E402
from loongcollector_tpu.pipeline.pipeline import \
    CollectionPipeline  # noqa: E402

REGEX_LINES = [b"abc 123", b"nope!", b"zz 15", b"yy 25", b"q 1",
               b"mixed 9x", b"deep 1000", b"a 0", b"longword 111111"]
DELIM_LINES = [b"ab,cd,ef", b"zz,1,2", b"NOPE,x,y", b"q,w", b"a,b,c,d",
               b",,", b"x,,z"]
GROK_LINES = [b"abc 123", b"abc def", b"!!", b"zz 9", b"word word",
              b"n 0x"]
JSON_LINES = [b'{"a": "x", "n": 1}', b'not json', b'{"a": "y", "n": 2}',
              b'{"a": "z\\tq", "extra": true}']
ML_LINES = [b"[1] start line", b"  at frame one", b"  at frame two",
            b"[2] other", b"loose", b"[3] tail", b"  at deep"]

FAMILIES = [
    ("regex", REGEX_LINES, [
        {"Type": "processor_filter_native",
         "Include": {"content": r"[a-z]+ \d+"}},
        {"Type": "processor_parse_regex_tpu",
         "Regex": r"([a-z]+) (\d+)", "Keys": ["word", "num"]},
        {"Type": "processor_filter_native", "Include": {"num": r"1\d*"}},
    ], True),
    ("delimiter", DELIM_LINES, [
        {"Type": "processor_filter_native",
         "Include": {"content": r"[a-z]*,.*"}},
        {"Type": "processor_parse_delimiter_tpu", "Separator": ",",
         "Keys": ["a", "b", "c"]},
    ], True),
    ("regex+grok", GROK_LINES, [
        {"Type": "processor_filter_native",
         "Include": {"content": r"\w+ .*"}},
        {"Type": "processor_grok",
         "Match": [r"%{WORD:w} %{INT:n}", r"%{WORD:w} %{WORD:v}"]},
    ], None),   # engagement depends on the grok set fusing on this host
    ("json", JSON_LINES, [
        {"Type": "processor_filter_native",
         "Include": {"content": r"\{.*"}},
        {"Type": "processor_parse_json_tpu"},
    ], False),  # parse_json has no resident stage form — must not fuse
    ("multiline", ML_LINES, [
        {"Type": "processor_split_multiline_log_string_native",
         "Multiline": {"StartPattern": r"\[\d+\] .*",
                       "ContinuePattern": r"\s+.*"}},
        {"Type": "processor_parse_regex_tpu",
         "Regex": r"(?s)\[(\d+)\] (.*)", "Keys": ["id", "body"]},
    ], None),
]


def make_group(lines) -> PipelineEventGroup:
    blob = b"".join(lines)
    sb = SourceBuffer(len(blob) + 256)
    g = PipelineEventGroup(sb)
    views = [sb.copy_string(ln) for ln in lines]
    g.set_columns(ColumnarLogs(
        offsets=np.array([v.offset for v in views], np.int32),
        lengths=np.array([len(ln) for ln in lines], np.int32),
        timestamps=np.full(len(lines), 1700000002, np.int64)))
    return g


def digest(group: PipelineEventGroup) -> str:
    cols = group.columns
    arena = group.source_buffer.as_array()
    h = hashlib.blake2b(digest_size=16)
    n = len(cols)
    h.update(b"n=%d;consumed=%d;" % (n, int(cols.content_consumed)))
    if not cols.content_consumed:
        for i in range(n):
            o, ln = int(cols.offsets[i]), int(cols.lengths[i])
            h.update(b"c:")
            h.update(arena[o:o + ln].tobytes())
            h.update(b";")
    for k, (offs, lens) in sorted(cols.fields.items()):
        h.update(b"f:" + k.encode() + b";")
        for i in range(n):
            ln = int(lens[i])
            if ln < 0:
                h.update(b"\x00-")
            else:
                h.update(arena[int(offs[i]):int(offs[i]) + ln].tobytes())
            h.update(b";")
    if cols.parse_ok is not None:
        h.update(b"ok:" + np.asarray(cols.parse_ok, np.uint8).tobytes())
    return h.hexdigest()


def run_family(name, lines, processors, fused: bool):
    os.environ["LOONG_FUSED"] = "1" if fused else "0"
    DevicePlane.reset_for_testing()
    p = CollectionPipeline()
    config = {"inputs": [], "processors": processors,
              "flushers": [{"Type": "flusher_stdout"}]}
    assert p.init(f"fused-eq-{name}-{int(fused)}", config), name
    plane = DevicePlane.instance()
    g = make_group(lines)
    fin = p.process_begin([g])
    if fin is not None:
        fin()
    engaged = bool(p._fused_runs) and fused and plane.dispatched_total() \
        and any(r.program().dispatch_count for r in p._fused_runs)
    return digest(g), bool(p._fused_runs), engaged


def main() -> int:
    models.set_columnar_enabled(True)
    failures = 0
    engaged_total = 0
    for name, lines, processors, want_fusable in FAMILIES:
        fp.reset_for_testing()
        d_fused, planned, engaged = run_family(name, lines, processors,
                                               fused=True)
        d_staged, _, _ = run_family(name, lines, processors, fused=False)
        status = "fused" if engaged else "per-stage"
        if d_fused != d_staged:
            print(f"FAIL [{name}] fused {d_fused} != staged {d_staged}")
            failures += 1
            continue
        if want_fusable is True and not engaged:
            print(f"FAIL [{name}] expected a fused run to engage "
                  f"(planned={planned})")
            failures += 1
            continue
        if want_fusable is False and planned:
            print(f"FAIL [{name}] must not plan a fused run")
            failures += 1
            continue
        engaged_total += int(engaged)
        print(f"ok [{name}] byte-identical ({status})")
    if failures:
        print(f"fused equivalence gate: {failures} family(ies) FAILED")
        return 1
    print(f"fused equivalence gate: {len(FAMILIES)} families "
          f"byte-identical, {engaged_total} with fusion engaged — OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
