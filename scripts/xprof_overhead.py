#!/usr/bin/env python
"""loongxprof-overhead smoke gate (wired into scripts/lint.sh).

The loongxprof contract (docs/observability.md) is that the DISABLED
device-timeline hooks cost one module-global read + branch per call —
the dispatch hot path (DevicePlane.submit / DeviceFuture.result) must
not slow down when the plane ships but stays off.  Same proof shape as
trace_overhead.py / prof_overhead.py:

1. **Per-hook microbench** — ns/call of the disabled hooks
   (`xprof.is_active`, `xprof.begin_dispatch`, `xprof.close_dispatch`,
   `xprof.current_dispatch`) under a generous absolute ceiling.

2. **Synthetic dispatch loop** — N submit/result round-trips through a
   private DevicePlane (trivial kernel, no threads), timed in two
   configurations, interleaved, best-of-N each:

     * ``disabled``  — hooks as shipped, LOONG_XPROF off (production);
     * ``baseline``  — the same hooks monkeypatched to bare no-op
       lambdas, i.e. the cheapest conceivable "xprof compiled out".

   Gate: MIN paired disabled/baseline ratio ≤ 1.05.  The enabled time is
   reported informationally — recording MAY cost; off MUST NOT.
"""

import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__file__), ".."))

N_DISPATCH = 3_000
REPEATS = 9
MAX_DISABLED_OVER_BASELINE = 1.05      # the 5% gate
MAX_HOOK_NS = 2_000                    # catastrophic-regression ceiling


def bench_hooks():
    from loongcollector_tpu.ops import xprof
    xprof.disable()
    out = {}
    for label, fn in (("is_active", xprof.is_active),
                      ("begin_dispatch", lambda: xprof.begin_dispatch(128)),
                      ("close_dispatch", lambda: xprof.close_dispatch(0)),
                      ("current_dispatch", xprof.current_dispatch)):
        n = 200_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        out[label] = best * 1e9
    return out


def make_runner():
    import numpy as np
    from loongcollector_tpu.ops.device_plane import DevicePlane
    plane = DevicePlane(budget_bytes=1 << 24)
    payload = np.arange(256, dtype=np.int32)

    def kernel(a):
        return (a,)

    def run_timed():
        t0 = time.perf_counter()
        for _ in range(N_DISPATCH):
            fut = plane.submit(kernel, (payload,), payload.nbytes)
            fut.result()
        return time.perf_counter() - t0

    return plane, run_timed


def main() -> int:
    from loongcollector_tpu.ops import xprof
    hooks = bench_hooks()
    print("disabled hook cost (ns/call): "
          + ", ".join(f"{k}={v:.0f}" for k, v in hooks.items()))
    bad = {k: v for k, v in hooks.items() if v > MAX_HOOK_NS}
    if bad:
        print(f"FAIL: disabled hooks over {MAX_HOOK_NS} ns: {bad}")
        return 1

    import gc
    plane, run_timed = make_runner()
    noop_zero = lambda *a, **k: 0                     # noqa: E731
    noop_none = lambda *a, **k: None                  # noqa: E731
    noop_false = lambda: False                        # noqa: E731
    real = (xprof.is_active, xprof.begin_dispatch, xprof.close_dispatch,
            xprof.note_dispatch, xprof.set_current_dispatch,
            xprof.current_dispatch, xprof.leg)

    def set_baseline():
        xprof.disable()
        xprof.is_active = noop_false
        xprof.begin_dispatch = noop_zero
        xprof.close_dispatch = noop_none
        xprof.note_dispatch = noop_none
        xprof.set_current_dispatch = noop_none
        xprof.current_dispatch = noop_zero
        xprof.leg = noop_none

    def restore():
        (xprof.is_active, xprof.begin_dispatch, xprof.close_dispatch,
         xprof.note_dispatch, xprof.set_current_dispatch,
         xprof.current_dispatch, xprof.leg) = real

    def set_disabled():
        restore()
        xprof.disable()

    def set_enabled():
        restore()
        xprof.enable()

    # Paired rounds, gate = MIN ratio (see trace_overhead.py for why:
    # co-tenant steal drifts absolute timings past 5%, but a real
    # disabled-path regression is systematic and fails every pair).
    dis_ratios, en_ratios = [], []
    try:
        run_timed()                                   # warm the path
        for i in range(REPEATS):
            pair = [("baseline", set_baseline), ("disabled", set_disabled)]
            if i % 2:                                 # kill position bias
                pair.reverse()
            times = {}
            for name, setup in pair + [("enabled", set_enabled)]:
                setup()
                gc.collect()
                times[name] = run_timed()
                xprof.disable()
            dis_ratios.append(times["disabled"] / times["baseline"])
            en_ratios.append(times["enabled"] / times["baseline"])
    finally:
        restore()
        xprof.disable()

    ratio = min(dis_ratios)
    print(f"{N_DISPATCH}-dispatch synthetic loop, {REPEATS} paired rounds: "
          f"disabled/baseline min={ratio:.3f} "
          f"median={sorted(dis_ratios)[len(dis_ratios) // 2]:.3f}  "
          f"enabled/baseline min={min(en_ratios):.3f}")
    if ratio > MAX_DISABLED_OVER_BASELINE:
        print(f"FAIL: disabled-path overhead {(ratio - 1) * 100:.1f}% "
              f"> {(MAX_DISABLED_OVER_BASELINE - 1) * 100:.0f}% in every "
              "round — the disabled timeline must stay one branch per hook")
        return 1
    print("xprof overhead OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
