#!/usr/bin/env python
"""loongfuse equivalence gate (scripts/lint.sh + tier-1).

Compiles the default grok vocabulary's composite patterns into fused
multi-accept DFAs, scans a fixed corpus through the fused scanner AND
through per-pattern Python `re`, and fails on ANY classification
disagreement.  This is the hard line under the whole fusion design: the
fused automaton must carry the ORIGINAL pattern semantics exactly —
a drifted rewrite would silently mis-gate extraction for every event.

Exit 0 = equivalent; exit 1 = disagreement (printed per line/pattern).
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from loongcollector_tpu.ops.regex import fuse  # noqa: E402
from loongcollector_tpu.ops.regex.grok import DEFAULT_PATTERNS, expand  # noqa: E402

# The default grok set under test: every composite vocabulary entry plus
# the multiline classics — the pattern shapes pipelines actually fuse.
GROK_SET = [
    expand("%{COMMONAPACHELOG}"),
    expand("%{COMBINEDAPACHELOG}"),
    expand("%{NGINXACCESS}"),
    expand("%{HTTPDATE}"),
    expand("%{TIMESTAMP_ISO8601}"),
    expand("%{SYSLOGTIMESTAMP}"),
    expand("%{LOGLEVEL}"),
    expand("%{URI}"),
    expand("%{DATESTAMP}"),
    expand("%{HOSTPORT}"),
]
MULTILINE_SET = [
    r"\d{4}-\d{2}-\d{2} .*",
    r"\s+at .*",
    r".*(?:Exception|Error).*",
    r"Caused by: .*",
]


def corpus() -> list:
    lines = [
        b'1.2.3.4 - frank [10/Oct/2000:13:55:36 -0700] "GET /a.gif HTTP/1.0" 200 2326',
        b'1.2.3.4 - frank [10/Oct/2000:13:55:36 -0700] "GET /a.gif HTTP/1.0" 200 2326 "http://r" "UA"',
        b'8.8.8.8 - - [01/Jan/2024:00:00:00 +0000] "POST /api HTTP/2.0" 404 0 "-" "-"',
        b'10/Oct/2000:13:55:36 -0700',
        b"2024-01-02T03:04:05.123+08:00",
        b"2024-01-02 03:04:05Z",
        b"Oct 11 22:14:15",
        b"Oct  1 02:04:05",
        b"ERROR", b"warning", b"Info", b"CRITICAL", b"waring", b"eror",
        b"http://user:pw@host.example.com:8080/path?q=1",
        b"ftp://files.example.com/",
        b"02/28/2024 13:55:36",
        b"host.example.com:443",
        b"2024-01-02 03:04:05 ERROR boom",
        b"  at com.example.Foo(Foo.java:10)",
        b"java.lang.IllegalStateException: bad",
        b"Caused by: java.io.IOException",
        b"plain text line",
        b"", b"-", b"0", b"[]", b'"',
    ]
    rng = np.random.default_rng(11)
    # byte fuzz: mutated copies catch boundary/class-compression drift
    for i in range(200):
        base = bytearray(lines[i % 28])
        if base:
            base[int(rng.integers(len(base)))] = int(rng.integers(256))
        lines.append(bytes(base))
    return lines


def check_set(name: str, patterns: list) -> int:
    lines = corpus()
    blob = b"".join(lines)
    arena = np.frombuffer(blob, dtype=np.uint8)
    lens = np.array([len(l) for l in lines], dtype=np.int32)
    offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64)

    fdfa = fuse.compile_fused(patterns, alarm_demotions=False)
    scanner = fuse.ByteTableScanner.from_fused(fdfa)
    tags = scanner.scan(arena, offs, lens)
    # the numpy lockstep fallback must agree with the native walk too
    tags_np = scanner._scan_numpy(
        arena, offs, lens, np.zeros(len(lines), np.uint32))

    res = [re.compile(p.encode("latin-1")) for p in fdfa.patterns]
    bad = 0
    for i, line in enumerate(lines):
        want = 0
        for b, r in enumerate(res):
            if r.fullmatch(line) is not None:
                want |= 1 << b
        for got, how in ((int(tags[i]), "native"), (int(tags_np[i]), "numpy")):
            if got != want:
                bad += 1
                print(f"FAIL[{name}/{how}] line {i!r}: fused tags "
                      f"{got:#x} != re {want:#x} ({line[:60]!r})")
    demoted = ", ".join(nm for nm, _, _ in fdfa.demoted) or "none"
    print(f"{name}: {len(fdfa.patterns)} fused ({fdfa.num_states} states, "
          f"{fdfa.num_classes} classes), demoted: {demoted}, "
          f"{len(lines)} lines x native+numpy — "
          f"{'OK' if not bad else f'{bad} DISAGREEMENTS'}")
    return bad


def main() -> int:
    bad = check_set("grok-default", GROK_SET)
    bad += check_set("multiline", MULTILINE_SET)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
