#!/usr/bin/env python
"""loongslo overhead smoke gate (wired into scripts/lint.sh).

The loongslo contract (docs/observability.md#freshness-slo-plane) follows
the chaos/trace/prof/ledger idiom: with ``LOONG_SLO`` off, every hook —
``slo.is_on``, ``slo.stamp_ingest``, ``slo.stamps_of``,
``slo.observe_stamps`` / ``observe_groups`` — is one module-global read +
branch.  Same two-layer proof as scripts/ledger_overhead.py, same
paired-min method:

1. **Per-hook microbench** — ns/call of the disabled hooks under a
   generous absolute ceiling (a disabled path that allocates, locks or
   stamps metadata blows through it immediately).

2. **Synthetic pipeline** — the stamp-hooked hot path (the
   ProcessQueueManager B_INGEST admit + pop + ProcessorInstance split
   stage + SLS serialization + the terminal observe hook) timed with
   hooks as shipped (plane disabled) vs the same hooks monkeypatched to
   bare no-ops, interleaved paired rounds; the gate is the MINIMUM paired
   disabled/baseline ratio (>5% in EVERY round fails).  The enabled time
   is reported informationally — enabling MAY cost, disabling MUST NOT.
"""

import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__file__), ".."))

N_GROUPS = 400
EVENTS_PER_GROUP = 24
REPEATS = 9
MAX_DISABLED_OVER_BASELINE = 1.05      # the 5% gate
MAX_HOOK_NS = 2_000                    # catastrophic-regression ceiling


def bench_hooks():
    from loongcollector_tpu.monitor import slo
    slo.disable()
    out = {}
    for label, fn in (("is_on", slo.is_on),
                      ("stamp_ingest", lambda: slo.stamp_ingest("p", None)),
                      ("stamps_of", lambda: slo.stamps_of(())),
                      ("observe_stamps", lambda: slo.observe_stamps(
                          "p", (), slo.OUTCOME_SEND_OK))):
        n = 200_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        out[label] = best * 1e9
    return out


def make_runner():
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    from loongcollector_tpu.monitor import slo
    from loongcollector_tpu.pipeline.plugin.instance import ProcessorInstance
    from loongcollector_tpu.pipeline.plugin.interface import PluginContext
    from loongcollector_tpu.pipeline.queue.process_queue_manager import \
        ProcessQueueManager
    from loongcollector_tpu.pipeline.serializer.sls_serializer import \
        SLSEventGroupSerializer
    from loongcollector_tpu.processor.split_log_string import \
        ProcessorSplitLogString
    inst = ProcessorInstance(ProcessorSplitLogString(), "split/slo_overhead")
    assert inst.init({}, PluginContext("slo_overhead"))
    ser = SLSEventGroupSerializer()
    line = b"2024-01-02 03:04:05 INFO request handled ok\n"
    data = line * EVENTS_PER_GROUP
    pqm = ProcessQueueManager()
    pqm.create_or_reuse_queue(1, capacity=4, pipeline_name="slo_overhead")

    def run_timed():
        t0 = time.perf_counter()
        for _ in range(N_GROUPS):
            sb = SourceBuffer(len(data) + 64)
            g = PipelineEventGroup(sb)
            g.add_raw_event(1).set_content(sb.copy_string(data))
            # the stamped admit (the single B_INGEST hook) → pop → stage →
            # payload → terminal observe: every loongslo hook in path
            assert pqm.push_queue(1, g)
            _, g = pqm.pop_item(timeout=0)
            inst.process([g])
            ser.serialize([g])
            if slo.is_on():
                slo.observe_groups("slo_overhead", [g],
                                   slo.OUTCOME_SEND_OK)
            assert len(g) == EVENTS_PER_GROUP
        return time.perf_counter() - t0

    return inst, run_timed


def main() -> int:
    from loongcollector_tpu.monitor import slo
    hooks = bench_hooks()
    print("disabled hook cost (ns/call): "
          + ", ".join(f"{k}={v:.0f}" for k, v in hooks.items()))
    bad = {k: v for k, v in hooks.items() if v > MAX_HOOK_NS}
    if bad:
        print(f"FAIL: disabled hooks over {MAX_HOOK_NS} ns: {bad}")
        return 1

    import gc
    inst, run_timed = make_runner()
    noop_false = lambda: False                        # noqa: E731
    noop_none = lambda *a, **k: None                  # noqa: E731
    noop_empty = lambda *a, **k: ()                   # noqa: E731
    real = (slo.is_on, slo.stamp_ingest, slo.cancel_group, slo.stamps_of,
            slo.observe_stamps, slo.observe_groups)

    def restore():
        (slo.is_on, slo.stamp_ingest, slo.cancel_group, slo.stamps_of,
         slo.observe_stamps, slo.observe_groups) = real

    def set_baseline():
        slo.disable()
        slo.is_on = noop_false
        slo.stamp_ingest = noop_none
        slo.cancel_group = noop_none
        slo.stamps_of = noop_empty
        slo.observe_stamps = noop_none
        slo.observe_groups = noop_none

    def set_disabled():
        restore()
        slo.disable()

    def set_enabled():
        restore()
        slo.enable()

    # Paired rounds, min ratio across rounds: a REAL disabled-path
    # regression is systematic and survives every pairing; co-tenant CPU
    # steal on a shared core does not (see scripts/ledger_overhead.py).
    dis_ratios, en_ratios = [], []
    try:
        run_timed()                                   # warm the path
        for i in range(REPEATS):
            pair = [("baseline", set_baseline), ("disabled", set_disabled)]
            if i % 2:                                 # kill position bias
                pair.reverse()
            times = {}
            for name, setup in pair + [("enabled", set_enabled)]:
                setup()
                gc.collect()
                times[name] = run_timed()
                slo.disable()
            dis_ratios.append(times["disabled"] / times["baseline"])
            en_ratios.append(times["enabled"] / times["baseline"])
    finally:
        restore()
        slo.disable()
        inst.metrics.mark_deleted()

    ratio = min(dis_ratios)
    print(f"{N_GROUPS}x{EVENTS_PER_GROUP}-event synthetic pipeline, "
          f"{REPEATS} paired rounds: "
          f"disabled/baseline min={ratio:.3f} "
          f"median={sorted(dis_ratios)[len(dis_ratios) // 2]:.3f}  "
          f"enabled/baseline min={min(en_ratios):.3f}")
    if ratio > MAX_DISABLED_OVER_BASELINE:
        print(f"FAIL: disabled-path overhead {(ratio - 1) * 100:.1f}% "
              f"> {(MAX_DISABLED_OVER_BASELINE - 1) * 100:.0f}% in every "
              "round — the disabled SLO plane must stay one branch per hook")
        return 1
    print("slo overhead OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
