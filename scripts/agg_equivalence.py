#!/usr/bin/env python
"""loongagg equivalence gate (scripts/lint.sh + tier-1).

Three hard lines under the windowed metric-rollup fold:

1. **Substrate equivalence** — the native ``lct_group_reduce``, the numpy
   twin and the device ``SegmentReduceKernel`` must agree over an
   adversarial corpus: identical row→group partition (first-seen order),
   identical invalid-row set, and identical aggregates.  Native vs numpy
   is compared BIT-IDENTICAL for every output including f64 sums (same
   accumulation order by construction).  The device twin reduces in f32
   on default-precision backends, so its sums compare within a stated
   tolerance; count/min/max/last/histogram compare exactly (min/max are
   selections — monotone under the f64→f32 cast — and bucket ids are
   computed host-side in f64 for every substrate).

2. **Path identity** — the full aggregator over the columnar plane and
   over the per-event dict path (the loongcolumn side-by-side contract)
   must emit byte-identical rollup groups: same keys, same windows, same
   formatted aggregate spans.  Both paths build per-batch partials first
   and merge with the same operation, so this equality is exact.

3. **Reference fold** — both paths must match a brute-force
   pure-Python reference fold over the same rows (sum within 1e-12
   relative — the reference accumulates in a different order — and
   count/min/max/last exactly).

Exit 0 = equivalent; exit 1 = any disagreement (printed per case).
"""

from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from loongcollector_tpu.ops.kernels import segment_reduce as sr  # noqa: E402


def batch_corpus():
    """[(label, rows, device_ok)] — rows are (name, labels tuple, value
    text, slot).  device_ok=False keeps f32-overflowing magnitudes out of
    the device comparison (documented f32 range)."""
    rng = np.random.default_rng(20260804)
    cases = []

    rows = [(b"reqs", (b"h1",), b"1", 0), (b"reqs", (b"h1",), b"2", 0),
            (b"reqs", (b"h2",), b"3.5", 0), (b"lat", (None,), b"0.25", 0),
            (b"reqs", (b"h1",), b"4", 1)]
    cases.append(("basic", rows, True))

    rows = [(b"m", (b"",), b"1", 0), (b"m", (None,), b"1", 0),
            (b"", (b"x",), b"2", 0), (b"m" * 61, (b"y" * 67,), b"3", 0),
            (b"ab", (b"",), b"5", 0), (b"a", (b"b",), b"5", 0)]
    cases.append(("absent-vs-empty keys, word-boundary lengths", rows, True))

    rows = [(b"v", (), b" 1.5 ", 0), (b"v", (), b"\t2e3\t", 0),
            (b"v", (), b"+.5", 0), (b"v", (), b"-0.0", 0),
            (b"v", (), b"1_0", 0), (b"v", (), b"0x10", 0),
            (b"v", (), b"nan", 0), (b"v", (), b"inf", 0),
            (b"v", (), b"-INF", 0), (b"v", (), b"Infinity", 0),
            (b"v", (), b"", 0), (b"v", (), b"  ", 0),
            (b"v", (), b"1e", 0), (b"v", (), b".", 0),
            (b"v", (), b"5.", 0), (b"v", (), b".5e-2", 0),
            (b"v", (), b"12345678901234567890", 0)]
    cases.append(("value grammar edge cases", rows, False))

    rows = [(b"big", (), b"1e300", 0), (b"big", (), b"1e300", 0),
            (b"tiny", (), b"1e-300", 0),
            (b"long", (), b"3." + b"1" * 120, 0)]
    cases.append(("magnitude extremes (host substrates only)", rows, False))

    names = [b"http_requests_total", b"cpu_seconds", b"gc_pause"]
    hosts = [b"h%d" % i for i in range(17)] + [None]
    rows = []
    for _ in range(3000):
        v = f"{rng.uniform(-100, 100):.6g}".encode()
        rows.append((names[rng.integers(len(names))],
                     (hosts[rng.integers(len(hosts))],
                      b"az%d" % rng.integers(3)),
                     v, int(rng.integers(0, 5))))
    cases.append(("random 3000x(3 names x 18 hosts x 3 az x 5 slots)",
                  rows, True))

    rows = [(b"one", (), b"%d" % i, i % 7) for i in range(257)]
    cases.append(("per-slot splits", rows, True))
    return cases


def pack_rows(rows):
    blob = bytearray()

    def put(b):
        if b is None:
            return (0, -1)
        off = len(blob)
        blob.extend(b)
        return (off, len(b))

    n = len(rows)
    K = 1 + max((len(r[1]) for r in rows), default=0)
    key_offs = np.zeros((n, K), np.int64)
    key_lens = np.full((n, K), -1, np.int32)
    val_offs = np.zeros(n, np.int64)
    val_lens = np.zeros(n, np.int32)
    slots = np.zeros(n, np.int64)
    for i, (nm, labels, v, slot) in enumerate(rows):
        key_offs[i, 0], key_lens[i, 0] = put(nm)
        for k, lb in enumerate(labels):
            key_offs[i, 1 + k], key_lens[i, 1 + k] = put(lb)
        val_offs[i], val_lens[i] = put(v)
        slots[i] = slot
    arena = (np.frombuffer(bytes(blob), np.uint8) if blob
             else np.zeros(0, np.uint8))
    return arena, slots, key_offs, key_lens, val_offs, val_lens


def check_substrates(cases) -> int:
    bad = 0
    kern = None
    for label, rows, device_ok in cases:
        args = pack_rows(rows)
        nat = sr.fold_batch_native(*args)
        ref = sr.fold_batch_numpy(*args)
        if nat is None:
            print(f"substrates[{label}]: native unavailable — SKIPPED")
        else:
            for field in ("group_id", "rep_row", "sum", "count", "min",
                          "max", "last", "hist"):
                a, b = getattr(nat, field), getattr(ref, field)
                # sums can be NaN by arithmetic (inf + -inf in one key)
                # even though NaN VALUES are grammar-invalid; bit-identity
                # still holds, so compare with equal_nan on floats
                eq = (np.array_equal(a, b, equal_nan=True)
                      if np.issubdtype(np.asarray(a).dtype, np.floating)
                      else np.array_equal(a, b))
                if not eq:
                    bad += 1
                    print(f"FAIL substrates[{label}] native!=numpy on "
                          f"{field}: {a[:8]} vs {b[:8]}")
        if device_ok:
            if kern is None:
                kern = sr.SegmentReduceKernel()
            dev = kern.fold_batch(*args[:6])
            for field in ("group_id", "rep_row", "count", "hist"):
                a, b = getattr(dev, field), getattr(ref, field)
                if not np.array_equal(a, b):
                    bad += 1
                    print(f"FAIL substrates[{label}] device!=numpy on "
                          f"{field}")
            for field in ("min", "max", "last"):
                a = getattr(dev, field)
                b = getattr(ref, field).astype(np.float32).astype(
                    np.float64)
                if not np.array_equal(a, b):
                    bad += 1
                    print(f"FAIL substrates[{label}] device {field} != "
                          f"f32(numpy {field})")
            if not np.allclose(dev.sum, ref.sum, rtol=1e-5, atol=1e-5):
                bad += 1
                print(f"FAIL substrates[{label}] device sums out of "
                      f"tolerance: max diff "
                      f"{np.max(np.abs(dev.sum - ref.sum))}")
    n_dev = sum(1 for c in cases if c[2])
    print(f"substrates: {len(cases)} corpora x native+numpy"
          f" (+device on {n_dev}) — {'OK' if not bad else f'{bad} DIFFS'}"
          + (f" (device dispatches: {kern.dispatch_count})" if kern
             else ""))
    return bad


# ---------------------------------------------------------------------------
# path identity: columnar vs per-event dict through the full aggregator


def make_columnar_group(rows, label_keys):
    from loongcollector_tpu.models import (ColumnarLogs, PipelineEventGroup,
                                           SourceBuffer)
    sb = SourceBuffer(4096)
    n = len(rows)
    cols_data = {k: ([0] * n, [-1] * n)
                 for k in ["__name__", "value"] + list(label_keys)}
    row_off = [0] * n
    tss = [0] * n

    def put(field, i, data):
        if data is None:
            return
        off = sb.allocate(len(data))
        sb.write_at(off, data)
        cols_data[field][0][i] = off
        cols_data[field][1][i] = len(data)

    for i, (nm, labels, v, ts) in enumerate(rows):
        put("__name__", i, nm)
        for k, lb in zip(label_keys, labels):
            put(k, i, lb)
        put("value", i, v)
        tss[i] = ts
    cols = ColumnarLogs(np.array(row_off, np.int32),
                        np.zeros(n, np.int32), np.array(tss, np.int64))
    cols.content_consumed = True
    for k, (o, ln) in cols_data.items():
        cols.set_field(k, np.array(o, np.int32), np.array(ln, np.int32))
    g = PipelineEventGroup(sb)
    g.set_columns(cols)
    return g


def make_dict_group(rows, label_keys):
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    sb = SourceBuffer(4096)
    g = PipelineEventGroup(sb)
    for nm, labels, v, ts in rows:
        ev = g.add_log_event(ts)
        if nm is not None:
            ev.set_content(b"__name__", sb.copy_string(nm))
        for k, lb in zip(label_keys, labels):
            if lb is not None:
                ev.set_content(k.encode(), sb.copy_string(lb))
        if v is not None:
            ev.set_content(b"value", sb.copy_string(v))
    return g


def rollup_rows_of(groups):
    """Canonical [(field, bytes...)] rows of emitted rollup groups, for
    byte-identity comparison across paths."""
    out = []
    for g in groups:
        cols = g.columns
        raw = g.source_buffer.raw
        names = sorted(cols.fields)
        for r in range(len(cols)):
            row = []
            for f in names:
                o, ln = cols.fields[f]
                if ln[r] < 0:
                    row.append((f, None))
                else:
                    row.append((f, bytes(raw[int(o[r]):
                                             int(o[r]) + int(ln[r])])))
            out.append(tuple(row))
    return sorted(out, key=repr)


def drive_path(rows, label_keys, columnar: bool, substrate: str):
    from loongcollector_tpu.aggregator.metric_rollup import \
        AggregatorMetricRollup
    from loongcollector_tpu.pipeline.plugin.interface import PluginContext
    agg = AggregatorMetricRollup()
    assert agg.init({"WindowSecs": 10, "SlideSecs": 5,
                     "AllowedLatenessSecs": 5,
                     "LabelKeys": list(label_keys),
                     "Substrate": substrate}, PluginContext("agg-gate"))
    emitted = []
    # three event-time-ordered batches: cross-batch partial merging is
    # exercised, while no row lands behind the watermark (the reference
    # fold below is drop-free; late-drop semantics are unit-tested)
    third = (max(r[3] for r in rows) + 1) // 3
    for chunk in (
            [r for r in rows if r[3] < third],
            [r for r in rows if third <= r[3] < 2 * third],
            [r for r in rows if r[3] >= 2 * third]):
        grp = (make_columnar_group(chunk, label_keys) if columnar
               else make_dict_group(chunk, label_keys))
        emitted.extend(agg.add(grp))
    emitted.extend(agg.flush())
    agg.metrics.mark_deleted()
    return rollup_rows_of(emitted)


def reference_fold(rows, label_keys):
    """Brute-force pure-Python fold (arbitrary but fixed accumulation
    order) — the semantic anchor both real paths must match."""
    state = {}
    for nm, labels, v, ts in rows:
        if nm is None or v is None:
            continue
        tok = v.strip(b" \t")
        if not sr._VALUE_RE.match(tok):
            continue
        val = float(tok)
        key = (nm, labels)
        per = state.setdefault(key, [])
        per.append((ts, val))
    out = {}
    for (nm, labels), pairs in state.items():
        vals = [v for _, v in pairs]
        out[(nm, labels)] = (math.fsum(vals), len(vals), min(vals),
                             max(vals), vals[-1])
    return out


def check_paths() -> int:
    rng = np.random.default_rng(7)
    names = [b"reqs", b"lat", None]
    hosts = [b"h1", b"h2", None]
    vals = [b"1", b"2.5", b"-3", b"bad", None, b"1e2", b"0.125"]
    rows = [(names[rng.integers(3)], (hosts[rng.integers(3)],),
             vals[rng.integers(len(vals))], int(rng.integers(0, 40)))
            for _ in range(800)]
    bad = 0
    from loongcollector_tpu.native import get_lib
    subs = ["numpy", "device"] + (["native"] if get_lib() else [])
    results = {}
    for sub in subs:
        results[("col", sub)] = drive_path(rows, ("host",), True, sub)
    results[("dict", "-")] = drive_path(rows, ("host",), False, "numpy")
    base = results[("col", "numpy")]
    for k, res in results.items():
        if k == ("col", "numpy"):
            continue
        exact = k != ("col", "device")
        if exact and res != base:
            bad += 1
            print(f"FAIL paths: {k} differs from columnar/numpy "
                  f"({len(res)} vs {len(base)} rows)")
            for a, b in zip(res, base):
                if a != b:
                    print(f"  first diff:\n    {a}\n    {b}")
                    break
        elif not exact:
            # device sums differ in f32; compare the exact columns only
            strip = {"sum", "min", "max", "last"}
            ra = [tuple((f, v) for f, v in row if f not in strip)
                  for row in res]
            rb = [tuple((f, v) for f, v in row if f not in strip)
                  for row in base]
            if ra != rb:
                bad += 1
                print(f"FAIL paths: {k} key/count/window columns differ")
    # semantic anchor: merge emitted windows back per key == reference
    ref = reference_fold(rows, ("host",))
    got = {}
    for row in base:
        d = dict(row)
        key = (d["__name__"], (d["host"],))
        s, c, mn, mx, last = got.get(key, (0.0, 0, None, None, None))
        got[key] = (s + float(d["sum"]), c + int(d["count"]),
                    min(mn, float(d["min"])) if mn is not None
                    else float(d["min"]),
                    max(mx, float(d["max"])) if mx is not None
                    else float(d["max"]), float(d["last"]))
    # sliding windows emit each slot window_s/slide_s times
    overlap = 2
    for key, (s, c, mn, mx, _last) in got.items():
        rs, rc, rmn, rmx, _rlast = ref[key]
        if c != rc * overlap or abs(s - rs * overlap) > 1e-9 * max(
                1.0, abs(rs)) or mn != rmn or mx != rmx:
            bad += 1
            print(f"FAIL reference fold mismatch for {key}: "
                  f"got {(s, c, mn, mx)} want x{overlap} of "
                  f"{(rs, rc, rmn, rmx)}")
    missing = set(ref) - set(got)
    if missing:
        bad += 1
        print(f"FAIL reference fold: keys never emitted: {missing}")
    print(f"paths: columnar({'/'.join(subs)}) vs dict vs reference over "
          f"{len(rows)} rows, sliding 10s/5s — "
          f"{'OK' if not bad else f'{bad} DIFFS'}")
    return bad


def main() -> int:
    bad = check_substrates(batch_corpus())
    bad += check_paths()
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
