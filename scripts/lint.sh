#!/usr/bin/env bash
# Repo lint gate — everything here also runs under tier-1 (the loonglint
# scan and the stress tests are pytest-gated), so this script is the fast
# local entry point, not the only enforcement.
#
#   1. loonglint: AST invariant checks over loongcollector_tpu/
#      (docs/static_analysis.md);
#   2. native hygiene: -Werror syntax pass + clang-tidy when installed;
#   3. ResourceWarning sweep: the concurrency stress tests under
#      `python -X dev -W error::ResourceWarning` — an unclosed socket,
#      file, or thread-local leak in the hot paths fails loudly here;
#   4. tracing-overhead smoke: loongtrace's disabled path must stay one
#      branch per hook (10k-event synthetic pipeline, disabled vs no-op
#      baseline, >5% regression fails — docs/observability.md).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== loonglint =="
# --budget caps the 14-checker sweep's own wall clock: the static gate
# stays a fast-feedback tool, and a checker that regresses to quadratic
# work fails here before it annoys every future lint run (per-checker
# timings: `python -m loongcollector_tpu.analysis --json` checker_seconds)
python -m loongcollector_tpu.analysis --budget 30 "$@"

echo "== tracing-overhead smoke =="
JAX_PLATFORMS=cpu python scripts/trace_overhead.py

echo "== profiler-overhead smoke (loongprof) =="
# with LOONG_PROF off the marker hooks must stay one branch per hook —
# same disabled-vs-noop-baseline >5% paired-min gate as the trace smoke
JAX_PLATFORMS=cpu python scripts/prof_overhead.py

echo "== ledger-overhead smoke (loongledger) =="
# with LOONG_LEDGER off the conservation-accounting hooks must stay one
# branch per hook — same paired-min >5% gate as the trace/prof smokes
JAX_PLATFORMS=cpu python scripts/ledger_overhead.py

echo "== slo-overhead smoke (loongslo) =="
# with LOONG_SLO off the ingest-stamp / terminal-observe hooks must stay
# one branch per hook — same paired-min >5% gate as the other planes
JAX_PLATFORMS=cpu python scripts/slo_overhead.py

echo "== xprof-overhead smoke (loongxprof) =="
# with LOONG_XPROF off the device-timeline hooks must stay one branch per
# hook on the dispatch hot path — same paired-min >5% gate, measured on a
# real DevicePlane submit/result loop
JAX_PLATFORMS=cpu python scripts/xprof_overhead.py

echo "== multi-worker smoke (loongshard) =="
# the disabled-trace overhead gate and the metric-naming checker must hold
# with the sharded plane active (LOONG_PROCESS_THREADS=4): the overhead
# budget is per-hook regardless of worker count, and every worker-owned
# metric record must still obey the naming/ownership rules
JAX_PLATFORMS=cpu LOONG_PROCESS_THREADS=4 python scripts/trace_overhead.py
LOONG_PROCESS_THREADS=4 python -m loongcollector_tpu.analysis \
    --checks metric-naming

echo "== columnar equivalence gate (loongcolumn) =="
# default pipeline chains through the columnar fast path AND the dict
# path; any sink-payload byte difference (or any per-event object minted
# on the columnar side) fails — docs/performance.md "Columnar event path"
JAX_PLATFORMS=cpu python scripts/columnar_equivalence.py

echo "== fused-DFA equivalence gate (loongfuse) =="
# the fused multi-accept automaton must classify EXACTLY like per-pattern
# `re` over the default grok set + multiline classics — any disagreement
# means fusion would mis-gate extraction (docs/performance.md)
JAX_PLATFORMS=cpu python scripts/fuse_equivalence.py

echo "== fused-pipeline equivalence gate (loongresident) =="
# the same processor chain with stage fusion ON (one fused device program
# per batch slot) and OFF (per-stage dispatch) must produce byte-identical
# groups across the regex / grok / delimiter / json / multiline families —
# fusion is an execution-plan change, never a semantics change
JAX_PLATFORMS=cpu python scripts/resident_equivalence.py

echo "== structural-index equivalence gate (loongstruct) =="
# the native/numpy/device structural bitmaps must be bit-identical, the
# JSON plane must match Python `json` row-for-row, and quote-mode
# delimiter parsing must reproduce the reference CSV FSM + python csv —
# any span or byte diff fails (docs/performance.md)
JAX_PLATFORMS=cpu python scripts/struct_equivalence.py

echo "== aggregation equivalence gate (loongagg) =="
# the native/numpy/device segment-reduce substrates must agree (numpy
# bit-identical incl. f64 sums, device exact on selections/counts), and
# the full rollup aggregator must emit byte-identical groups over the
# columnar and per-event dict paths — docs/performance.md
JAX_PLATFORMS=cpu python scripts/agg_equivalence.py

echo "== reload-soak smoke (loongtenant) =="
# sustained config churn under sustained ingest with the live ledger +
# auditor: any nonzero tenant residual, lost event, or failed reload of a
# valid config exits nonzero (docs/robustness.md "Hot reload")
JAX_PLATFORMS=cpu python scripts/reload_soak.py \
    --tenants 4 --rate 5 --seconds 3

echo "== crash-storm smoke (loongcrash) =="
# one seeded SIGKILL of the real agent at the send boundary, then restart
# + drain: zero loss byte-for-byte, duplicates bounded, ledger residual 0
# (docs/robustness.md "Crash durability"; full 8-seed matrix in soak.sh)
JAX_PLATFORMS=cpu python scripts/crash_storm.py --seed 3 --lines 120

echo "== native lint =="
make -C native lint

echo "== native sanitizer plane (ASan+UBSan) =="
# instrumented rebuild of the data plane driven through the native test
# corpus + the four equivalence gates (scripts/sanitize.sh); probe-gated
# so boxes without g++/libasan still lint
if scripts/sanitize.sh --probe >/dev/null 2>&1; then
    scripts/sanitize.sh
else
    echo "no sanitizer toolchain; skipped (scripts/sanitize.sh --probe)"
fi

echo "== ResourceWarning sweep (concurrency stress) =="
JAX_PLATFORMS=cpu python -X dev -W error::ResourceWarning -m pytest \
    tests/test_concurrency_stress.py tests/test_queues.py \
    -q -m 'not slow' -p no:cacheprovider

echo "lint OK"
