#!/bin/bash
# TPU-tunnel liveness watcher + DEAD->ALIVE capture trigger.
#
# Probes the tunnel every ~2 min:
#   * live  -> touch /tmp/tpu_alive (consumed by utils/backend.py for an
#              instant routing answer — no 90 s probe timeouts)
#   * dead  -> remove /tmp/tpu_alive
# and logs every probe to /tmp/tpu_watch.log.
#
# On a DEAD->ALIVE transition (or first live probe after start) it launches
# loongcollector_tpu.utils.tpu_capture, which runs the Pallas smoke,
# bench.py, and dryrun_multichip, persisting TPU_CAPTURE_LAST.json +
# BENCH_TPU_LAST_GOOD.json — so any availability window yields fresh
# on-silicon artifacts with no human in the loop.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
prev=unknown
while true; do
  # single-core host: a jax-importing probe steals CPU from a live bench —
  # yield while one runs (the capture path relaunches bench itself anyway).
  # Anchored pattern: an unanchored "bench.py" also matches unrelated
  # processes that merely mention the file in their argv.
  if pgrep -f '^[^ ]*python[0-9.]* ([^ ]*/)?bench\.py' > /dev/null 2>&1; then
    sleep 30
    continue
  fi
  ts=$(date -u +%H:%M:%S)
  if timeout 75 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
jnp.zeros(8).block_until_ready()
assert d.platform == 'tpu'
print(d)
" > /tmp/tpu_probe_out 2>&1; then
    echo "$ts ALIVE $(tail -1 /tmp/tpu_probe_out)" >> /tmp/tpu_watch.log
    touch /tmp/tpu_alive
    if [ "$prev" != "alive" ]; then
      echo "$ts TRANSITION dead->alive: launching capture" >> /tmp/tpu_watch.log
      (cd "$REPO" && nohup python -m loongcollector_tpu.utils.tpu_capture \
         >> /tmp/tpu_capture.log 2>&1 &)
    fi
    prev=alive
  else
    echo "$ts DEAD" >> /tmp/tpu_watch.log
    rm -f /tmp/tpu_alive
    prev=dead
  fi
  sleep 110
done
