#!/bin/bash
# Opportunistic TPU bench: probe the tunnel; on the first healthy probe run
# bench.py (which persists BENCH_TPU_LAST_GOOD.json) and exit.
cd /root/repo
for i in $(seq 1 120); do
  if timeout 60 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
jnp.zeros(8).block_until_ready()
assert d.platform == 'tpu'
" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) TPU back; running bench" >> /tmp/tpu_watch.log
    timeout 1500 python bench.py > /tmp/tpu_bench_opportunistic.json 2>/tmp/tpu_bench_opportunistic.err
    echo "$(date +%H:%M:%S) bench rc=$?" >> /tmp/tpu_watch.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) probe $i: down" >> /tmp/tpu_watch.log
  sleep 180
done
exit 1
