#!/bin/bash
# Probe the TPU tunnel every ~2 min; log transitions to /tmp/tpu_watch.log.
# When the tunnel comes alive, touch /tmp/tpu_alive so the builder can react.
while true; do
  ts=$(date -u +%H:%M:%S)
  if timeout 75 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
jnp.zeros(8).block_until_ready()
assert d.platform == 'tpu'
print(d)
" > /tmp/tpu_probe_out 2>&1; then
    echo "$ts ALIVE $(tail -1 /tmp/tpu_probe_out)" >> /tmp/tpu_watch.log
    touch /tmp/tpu_alive
  else
    echo "$ts DEAD" >> /tmp/tpu_watch.log
    rm -f /tmp/tpu_alive
  fi
  sleep 110
done
