#!/usr/bin/env python
"""Tracing-overhead smoke gate (wired into scripts/lint.sh).

The loongtrace contract (docs/observability.md) is that DISABLED tracing
costs one module-global read + branch per hook.  This script proves it two
ways and exits non-zero when the contract regresses:

1. **Per-hook microbench** — ns/call of the disabled hooks
   (`trace.is_active`, `trace.event`, `trace.start_span`) with a generous
   absolute ceiling: a regression that makes the disabled path allocate
   or take locks blows through it immediately.

2. **10k-event synthetic pipeline** — the real instrumented path
   (ProcessorInstance split stage + SLS serialization, no threads so the
   measurement is deterministic) timed in two configurations,
   interleaved, best-of-N each:

     * ``disabled``  — hooks as shipped, tracer off (the production path);
     * ``baseline``  — the same hooks monkeypatched to bare no-op
       lambdas, i.e. the cheapest conceivable "tracing compiled out".

   Gate: disabled must be within 5% of baseline.  The tracer-enabled
   time is also measured and reported (informational — enabling tracing
   MAY cost; disabling it MUST NOT).
"""

import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__file__), ".."))

N_EVENTS = 10_000
REPEATS = 9
MAX_DISABLED_OVER_BASELINE = 1.05      # the 5% gate
MAX_HOOK_NS = 2_000                    # catastrophic-regression ceiling


def bench_hooks():
    from loongcollector_tpu import trace
    trace.disable()
    out = {}
    for label, fn in (("is_active", trace.is_active),
                      ("event", lambda: trace.event("x")),
                      ("start_span", lambda: trace.start_span("x"))):
        n = 200_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        out[label] = best * 1e9
    return out


def make_runner():
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    from loongcollector_tpu.pipeline.plugin.instance import ProcessorInstance
    from loongcollector_tpu.pipeline.plugin.interface import PluginContext
    from loongcollector_tpu.pipeline.serializer.sls_serializer import \
        SLSEventGroupSerializer
    from loongcollector_tpu.processor.split_log_string import \
        ProcessorSplitLogString
    inst = ProcessorInstance(ProcessorSplitLogString(), "split/overhead")
    assert inst.init({}, PluginContext("overhead"))
    ser = SLSEventGroupSerializer()
    line = b"2024-01-02 03:04:05 INFO request handled ok\n"
    data = line * N_EVENTS

    def run_timed():
        sb = SourceBuffer(len(data) + 64)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1).set_content(sb.copy_string(data))
        t0 = time.perf_counter()
        inst.process([g])
        ser.serialize([g])
        dt = time.perf_counter() - t0
        assert len(g) == N_EVENTS
        return dt

    return inst, run_timed


def main() -> int:
    from loongcollector_tpu import trace
    hooks = bench_hooks()
    print("disabled hook cost (ns/call): "
          + ", ".join(f"{k}={v:.0f}" for k, v in hooks.items()))
    bad = {k: v for k, v in hooks.items() if v > MAX_HOOK_NS}
    if bad:
        print(f"FAIL: disabled hooks over {MAX_HOOK_NS} ns: {bad}")
        return 1

    import gc
    inst, run_timed = make_runner()
    noop_active = lambda: False                       # noqa: E731
    noop_none = lambda *a, **k: None                  # noqa: E731
    real = (trace.is_active, trace.start_span, trace.active_tracer)

    def set_baseline():
        trace.disable()
        trace.is_active = noop_active
        trace.start_span = noop_none
        trace.active_tracer = noop_none

    def set_disabled():
        trace.is_active, trace.start_span, trace.active_tracer = real
        trace.disable()

    def set_enabled():
        trace.is_active, trace.start_span, trace.active_tracer = real
        trace.enable()

    # Paired rounds: on a shared single core, absolute ms-scale timings
    # drift more than the 5% budget (co-tenant steal), but a REAL
    # disabled-path regression is systematic — it shows up in EVERY
    # baseline/disabled pair measured back-to-back.  So the gate is the
    # MINIMUM paired ratio across rounds: if even one round ran the
    # shipped hooks within 5% of the no-op baseline, the hooks are one
    # branch; sustained overhead fails all rounds and trips the gate.
    dis_ratios, en_ratios = [], []
    try:
        run_timed()                                   # warm the path
        for i in range(REPEATS):
            pair = [("baseline", set_baseline), ("disabled", set_disabled)]
            if i % 2:                                 # kill position bias
                pair.reverse()
            times = {}
            for name, setup in pair + [("enabled", set_enabled)]:
                setup()
                gc.collect()
                times[name] = run_timed()
                trace.disable()
            dis_ratios.append(times["disabled"] / times["baseline"])
            en_ratios.append(times["enabled"] / times["baseline"])
    finally:
        trace.is_active, trace.start_span, trace.active_tracer = real
        trace.disable()
        inst.metrics.mark_deleted()

    ratio = min(dis_ratios)
    print(f"{N_EVENTS}-event synthetic pipeline, {REPEATS} paired rounds: "
          f"disabled/baseline min={ratio:.3f} "
          f"median={sorted(dis_ratios)[len(dis_ratios) // 2]:.3f}  "
          f"enabled/baseline min={min(en_ratios):.3f}")
    if ratio > MAX_DISABLED_OVER_BASELINE:
        print(f"FAIL: disabled-path overhead {(ratio - 1) * 100:.1f}% "
              f"> {(MAX_DISABLED_OVER_BASELINE - 1) * 100:.0f}% in every "
              "round — the disabled tracer must stay one branch per hook")
        return 1
    rc = smoke_multiworker()
    if rc:
        return rc
    print("trace overhead OK")
    return 0


def smoke_multiworker() -> int:
    """loongshard smoke (lint.sh runs this file with
    LOONG_PROCESS_THREADS=4): with the sharded plane active, a burst of
    multi-source groups must drain losslessly, in per-source order, and
    the runner must stop cleanly.  No-op when the env var is absent or 1
    (the single-worker path is what the paired rounds above measured)."""
    import os
    import time as _time
    if int(os.environ.get("LOONG_PROCESS_THREADS", "1") or "1") <= 1:
        return 0
    from loongcollector_tpu.pipeline.pipeline_manager import (
        CollectionPipelineManager, ConfigDiff)
    from loongcollector_tpu.pipeline.queue.process_queue_manager import \
        ProcessQueueManager
    from loongcollector_tpu.pipeline.queue.sender_queue import \
        SenderQueueManager
    from loongcollector_tpu.runner.processor_runner import (
        ProcessorRunner, resolve_thread_count)
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    tc = resolve_thread_count()
    pqm = ProcessQueueManager()
    mgr = CollectionPipelineManager(pqm, SenderQueueManager())
    runner = ProcessorRunner(pqm, mgr, thread_count=tc)
    runner.init()
    diff = ConfigDiff()
    diff.added["overhead-shard"] = {
        "inputs": [{"Type": "input_static_file_onetime",
                    "FilePaths": ["/nonexistent"]}],
        "processors": [],
        "flushers": [{"Type": "flusher_blackhole"}],
    }
    mgr.update_pipelines(diff)
    p = mgr.find_pipeline("overhead-shard")
    bh = p.flushers[0].plugin
    n_groups, per_group = 48, 32
    line = b"2024-01-02 03:04:05 INFO shard smoke\n"
    try:
        for i in range(n_groups):
            payload = line * per_group
            sb = SourceBuffer(len(payload) + 64)
            g = PipelineEventGroup(sb)
            g.add_raw_event(1).set_content(sb.copy_string(payload))
            g.set_tag(b"__source__", b"smoke-%d" % (i % 6))
            deadline = _time.monotonic() + 20
            while not pqm.push_queue(p.process_queue_key, g):
                if _time.monotonic() > deadline:
                    print("FAIL: multi-worker smoke push starved")
                    return 1
                _time.sleep(0.002)
        deadline = _time.monotonic() + 30
        while bh.total_events < n_groups and \
                _time.monotonic() < deadline:
            _time.sleep(0.01)
        if bh.total_events < n_groups:
            print(f"FAIL: multi-worker smoke lost groups "
                  f"({bh.total_events}/{n_groups} reached the sink)")
            return 1
    finally:
        runner.stop()
        mgr.stop_all()
    print(f"multi-worker smoke OK ({tc} workers, {n_groups} groups)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
