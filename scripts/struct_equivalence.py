#!/usr/bin/env python
"""loongstruct equivalence gate (scripts/lint.sh + tier-1).

Three hard lines under the structural-index parsing plane:

1. **Index equivalence** — the native `lct_struct_index` bitmaps, the
   numpy twin, and the device kernel (jitted, CPU backend here) must be
   bit-identical over an adversarial corpus, in both JSON and delimiter
   modes.  Any differing word means the three substrates disagree about
   where strings/structural characters are — the codesign contract is
   "same index, different execution", never "similar index".

2. **JSON differential** — `processor_parse_json_tpu` over the structural
   plane must agree with Python's `json` module row for row: the same
   accept/reject set, and byte-identical values for strings (including
   escape decoding into the side arena), bools, nulls and
   canonically-spelled numbers.  Nested containers compare semantically
   (raw-span vs json.dumps spelling is the documented contract).

3. **Delimiter differential** — quote-mode parsing (native fused AND the
   no-native numpy tier) must reproduce the reference CSV FSM
   (`_csv_fsm_split`) field-for-field, and agree with Python's `csv`
   module on the well-formed subset.

Exit 0 = equivalent; exit 1 = any span or byte diff (printed per row).
"""

from __future__ import annotations

import csv
import io
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from loongcollector_tpu import native as nat  # noqa: E402
from loongcollector_tpu.ops.kernels import struct_index as si  # noqa: E402


def json_corpus() -> list:
    rows = [
        b'{"ts": 1700000000, "level": "info", "user": "u1", "msg": "hi"}',
        b'{"ts": 1, "level": "in\\nfo", "user": "u\\u00e9", "msg": "\\"q\\""}',
        b'{"ts": 2, "level": "ok", "user": "u", "msg": "m"}',
        b'{"ts": 1, "extra_key": "boom", "level": "x"}',
        b'{"nested": {"a": [1, 2, {"b": "c,{}"}]}, "ts": 3}',
        b'{"ts": bad}', b'not json', b'{}', b'  { } ',
        b'{"a": "unterminated', b'{"dup": 1, "dup": 2}',
        b'{"sp" :  "v"  ,  "n" : -1.5e3  }',
        b'{"surrogate": "\\ud83d\\ude00"}',
        b'{"slash": "a\\/b"}', b'{"ctl": "a\tb"}',
        b'{"a": 1} trailing', b'{"a": 1}}', b'{"a": 01}', b'{"a"::1}',
        b'{"a": 1, }', b'{"a" x: 1}', b'{"a": "x" junk "y"}',
        b'{"a": true, "b": null, "c": false}', b'[1, 2]', b'"str"', b'',
        b'{"reorder": 1, "ts": 2, "level": "z", "user": "u", "msg": "m"}',
        b'{"deep": ' + b'[' * 70 + b']' * 70 + b'}',
    ]
    # trailing-backslash runs crossing the 64-bit word boundary: the
    # escape-carry resolution's hardest case
    for k in range(1, 12):
        pad = b'x' * (62 - k)
        rows.append(b'{"e": "' + pad + b'\\' * k + b'n", "t": 1}')
        rows.append(b'{"e": "' + pad + b'\\' * k + b'"}')  # some malformed
    rng = np.random.default_rng(12)
    for _ in range(300):
        L = int(rng.integers(0, 150))
        rows.append(bytes(rng.choice(
            list(b'ab\\"{}[]:, \t019.e-u'), size=L).astype(np.uint8)))
    return rows


def csv_corpus() -> list:
    rows = [
        b'a,b,c', b'"a,b",c', b'"a""b",c', b'a"b,c"d,e', b'"x"tail,y',
        b'"unterminated, z', b'', b',', b',,,', b'a,,b', b'"",x', b'""a,b',
        b'"a","b","c","d"', b'q,"r,s,t', b'"dq""""x",y', b'one',
        b'a,b,c,d,e,f,g,h', b'"j1,j2",k,"l,m",n,extra1,extra2',
    ]
    rng = np.random.default_rng(13)
    for _ in range(300):
        L = int(rng.integers(0, 80))
        rows.append(bytes(rng.choice(
            list(b'ab",x '), size=L).astype(np.uint8)))
    return rows


def pack(rows):
    blob = b"".join(rows)
    arena = np.frombuffer(blob, dtype=np.uint8) if blob \
        else np.zeros(0, np.uint8)
    lens = np.array([len(r) for r in rows], dtype=np.int32)
    offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64) \
        if rows else np.zeros(0, np.int64)
    return blob, arena, offs, lens


def check_index(rows, mode_i, mode_s, sep=0x2C) -> int:
    """Native vs numpy vs device masks, bit for bit."""
    blob, arena, offs, lens = pack(rows)
    nm = nat.struct_index(arena, offs, lens, mode=mode_i, sep=sep)
    if nm is None:
        print(f"index[{mode_s}]: native library unavailable — SKIPPED")
        return 0
    L = max(1, int(lens.max()))
    n = len(rows)
    mat = np.zeros((n, L), dtype=np.uint8)
    for i, r in enumerate(rows):
        mat[i, : len(r)] = np.frombuffer(r, dtype=np.uint8)
    np16 = si.struct_index_numpy(mat, lens, mode=mode_s, sep=sep)
    kern = si.StructIndexKernel(mode=mode_s, sep=sep)
    dv = [np.asarray(x) for x in kern(mat, lens)]
    W16 = np16[0].shape[1]
    bad = 0
    names = ("in_string", "structural", "escaped", "quote")
    for mi, name in enumerate(names):
        a = si.native_masks_as_words16(nm[mi])[:, :W16]
        b, c = np16[mi], dv[mi]
        if not (np.array_equal(a, b) and np.array_equal(b, c)):
            for i in range(n):
                if not (np.array_equal(a[i], b[i])
                        and np.array_equal(b[i], c[i])):
                    bad += 1
                    print(f"FAIL index[{mode_s}/{name}] row {i} "
                          f"{rows[i][:60]!r}: native/numpy/device disagree")
    print(f"index[{mode_s}]: {n} rows x native+numpy+device — "
          f"{'OK' if not bad else f'{bad} DISAGREEMENTS'} "
          f"(device dispatches: {kern.dispatch_count})")
    return bad


def check_json(rows) -> int:
    """Structural processor vs Python json over the corpus."""
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    from loongcollector_tpu.pipeline.plugin.interface import PluginContext
    from loongcollector_tpu.processor.parse_json import ProcessorParseJson

    rows = [r for r in rows if b"\n" not in r]
    data = b"\n".join(rows) + b"\n"
    sb = SourceBuffer(len(data) + 64)
    g = PipelineEventGroup(sb)
    g.add_raw_event(1).set_content(sb.copy_string(data))
    from loongcollector_tpu.processor.split_log_string import \
        ProcessorSplitLogString
    ctx = PluginContext("struct-gate")
    sp = ProcessorSplitLogString(); sp.init({}, ctx)
    pj = ProcessorParseJson(); pj.init({}, ctx)
    sp.process(g)
    pj.process(g)
    bad = 0
    for i, ev in enumerate(g.events):
        got = {str(k): str(v) for k, v in ev.contents if str(k) != "rawLog"}
        try:
            obj = json.loads(rows[i])
            ok = isinstance(obj, dict)
        except Exception:  # noqa: BLE001
            ok = False
        if not ok:
            if got:
                bad += 1
                print(f"FAIL json row {i} {rows[i][:60]!r}: python rejects, "
                      f"struct parsed {got}")
            continue
        for k, v in obj.items():
            if k not in got:
                bad += 1
                print(f"FAIL json row {i} {rows[i][:60]!r}: missing {k!r}")
                continue
            if isinstance(v, str):
                want = v
            elif isinstance(v, bool):
                want = "true" if v else "false"
            elif v is None:
                want = "null"
            elif isinstance(v, (dict, list)):
                # raw-span contract: compare semantically
                try:
                    if json.loads(got[k]) != v:
                        bad += 1
                        print(f"FAIL json row {i} key {k!r}: nested "
                              f"{got[k]!r} != {v!r}")
                except Exception:  # noqa: BLE001
                    bad += 1
                    print(f"FAIL json row {i} key {k!r}: nested span "
                          f"unparseable {got[k]!r}")
                continue
            else:
                continue  # numbers: raw-token spelling contract
            if got[k] != want:
                bad += 1
                print(f"FAIL json row {i} {rows[i][:60]!r} key {k!r}: "
                      f"{got[k]!r} != {want!r}")
        for k in got:
            if k not in obj:
                bad += 1
                print(f"FAIL json row {i}: phantom key {k!r}")
    print(f"json: {len(rows)} rows vs Python json — "
          f"{'OK' if not bad else f'{bad} DIFFS'}")
    return bad


def check_csv(rows) -> int:
    """Native + numpy-tier quote-mode parse vs the FSM, and vs Python csv
    on the well-formed subset."""
    from loongcollector_tpu.processor.parse_delimiter import _csv_fsm_split
    blob, arena, offs, lens = pack(rows)
    bad = 0
    for F in (2, 4, 6):
        res = nat.delim_struct_parse(arena, offs, lens, 0x2C, 0x22, F)
        if res is None:
            print("csv: native library unavailable — SKIPPED")
            break
        o_, l_, nf, side = res
        AL = len(arena)
        for i, r in enumerate(rows):
            fields = _csv_fsm_split(r, b",")
            if int(nf[i]) != len(fields):
                bad += 1
                print(f"FAIL csv row {i} {r[:50]!r}: nfields {int(nf[i])} "
                      f"!= {len(fields)}")
            want = fields if len(fields) <= F \
                else fields[: F - 1] + [b",".join(fields[F - 1:])]
            for k in range(min(F, len(want))):
                o2, l2 = int(o_[i, k]), int(l_[i, k])
                got = None if l2 < 0 else (
                    bytes(side[o2 - AL: o2 - AL + l2]) if o2 >= AL
                    else blob[o2: o2 + l2])
                if got != want[k]:
                    bad += 1
                    print(f"FAIL csv row {i} {r[:50]!r} F={F} field {k}: "
                          f"{got!r} != {want[k]!r}")
    # Python csv agreement on the well-formed subset (no stray quotes)
    for r in rows:
        try:
            text = r.decode("utf-8")
        except UnicodeDecodeError:
            continue
        fsm = [f.decode("utf-8", "replace")
               for f in _csv_fsm_split(r, b",")]
        try:
            parsed = next(csv.reader(io.StringIO(text)))
        except (csv.Error, StopIteration):
            continue
        # csv and the FSM agree exactly on RFC4180-clean rows; rows with
        # literal mid-field quotes differ by documented design
        clean = all(('"' not in f) or text.count('"') % 2 == 0
                    for f in parsed) and '"' not in text.replace('""', '') \
            .replace('","', ',').strip('"')
        if clean and parsed != fsm and text:
            bad += 1
            print(f"FAIL csv-vs-python {r[:50]!r}: csv {parsed} fsm {fsm}")
    print(f"csv: {len(rows)} rows x F=2/4/6 vs FSM + python csv — "
          f"{'OK' if not bad else f'{bad} DIFFS'}")
    return bad


def main() -> int:
    jrows = json_corpus()
    crows = csv_corpus()
    bad = check_index(jrows, 0, si.MODE_JSON)
    bad += check_index(crows, 1, si.MODE_DELIM)
    bad += check_json(jrows)
    bad += check_csv(crows)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
