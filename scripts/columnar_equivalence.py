#!/usr/bin/env python
"""loongcolumn equivalence gate (scripts/lint.sh + tier-1).

Runs default pipeline chains (line split → regex / JSON / delimiter /
multiline parse) over fixed corpora through BOTH event paths —

* **columnar**: groups stay arena-span columns end-to-end (the shipping
  fast path; the run must mint ZERO per-event objects), and
* **dict**: ``set_columnar_enabled(False)`` — every instance boundary
  materializes per-event LogEvents and the sinks serialize row objects
  (the pre-loongcolumn shape),

then assembles every NDJSON/wire-riding sink payload (file/stdout/kafka
JSON lines, SLS PB, ClickHouse/Doris JSONEachRow, Elasticsearch bulk,
Loki push) from each and fails on ANY byte difference.  This is the hard
line under the zero-materialization design: the columnar plane must be
a pure representation change — byte-identical output, just without the
per-event Python objects.

Exit 0 = equivalent everywhere; exit 1 = at least one divergence.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from loongcollector_tpu import models  # noqa: E402
from loongcollector_tpu.models import (PipelineEventGroup,  # noqa: E402
                                       SourceBuffer)
from loongcollector_tpu.pipeline.plugin.instance import \
    ProcessorInstance  # noqa: E402
from loongcollector_tpu.pipeline.plugin.interface import \
    PluginContext  # noqa: E402
from loongcollector_tpu.pipeline.serializer.batch_json import \
    ndjson_payload  # noqa: E402
from loongcollector_tpu.pipeline.serializer.json_serializer import \
    JsonSerializer  # noqa: E402
from loongcollector_tpu.pipeline.serializer.sls_serializer import \
    SLSEventGroupSerializer  # noqa: E402

APACHE = (r'(\S+) (\S+) (\S+) \[([^\]]+)\] '
          r'"(\S+) (\S+) ([^"]*)" (\d{3}) (\d+)')
APACHE_KEYS = ["ip", "ident", "user", "time", "method", "url", "proto",
               "status", "size"]


def _corpus_apache() -> bytes:
    rows = []
    for i in range(200):
        rows.append(
            b'10.0.%d.%d - u%d [10/Oct/2000:13:55:%02d -0700] '
            b'"GET /p%d HTTP/1.1" %d %d'
            % (i % 256, (i * 7) % 256, i % 97, i % 60, i, 200 + i % 300,
               i * 13))
        if i % 9 == 0:
            rows.append(b"!! unparseable line %d" % i)   # keep-as-rawLog
    return b"\n".join(rows) + b"\n"


def _corpus_json() -> bytes:
    rows = [(b'{"ts": %d, "level": "info", "user": "u%d", "msg": "ok %d"}'
             % (1700000000 + i, i % 31, i)) for i in range(150)]
    rows.append(b"not json at all")
    return b"\n".join(rows) + b"\n"


def _corpus_delimiter() -> bytes:
    rows = [b"f%d,bar%d,baz%d" % (i, i * 3, i * 7) for i in range(150)]
    rows.append(b"short")
    return b"\n".join(rows) + b"\n"


def _corpus_multiline() -> bytes:
    rows = []
    for i in range(80):
        rows.append(b"2024-01-02 03:04:%02d ERROR boom %d" % (i % 60, i))
        rows.append(b"  at com.example.Foo(Foo.java:%d)" % i)
        rows.append(b"  at com.example.Bar(Bar.java:%d)" % (i * 2))
    return b"\n".join(rows) + b"\n"


def _corpus_nonascii() -> bytes:
    rows = [("naïve %d — ünïcode ✓" % i).encode("utf-8")
            for i in range(40)]
    return b"\n".join(rows) + b"\n"


def _chains():
    """(name, corpus, processor configs) — representative default
    pipelines; fresh plugin instances per run (multiline carries state)."""
    return [
        ("plain", _corpus_apache(), []),
        ("regex", _corpus_apache(),
         [{"Type": "processor_parse_regex_tpu", "Regex": APACHE,
           "Keys": APACHE_KEYS}]),
        ("json", _corpus_json(), [{"Type": "processor_parse_json_tpu"}]),
        ("delimiter", _corpus_delimiter(),
         [{"Type": "processor_parse_delimiter_tpu", "Separator": ",",
           "Keys": ["a", "b", "c"]}]),
        ("multiline", _corpus_multiline(),
         [{"Type": "processor_split_multiline_log_string_native",
           "Multiline": {"StartPattern": r"\d{4}-\d{2}-\d{2} .*"}}]),
        ("nonascii", _corpus_nonascii(), []),
    ]


def _build_chain(proc_cfgs):
    from loongcollector_tpu.pipeline.plugin.registry import PluginRegistry
    reg = PluginRegistry.instance()
    reg.load_static_plugins()
    ctx = PluginContext("columnar-equiv")
    insts = []
    split = reg.create_processor("processor_split_log_string_native")
    assert split is not None and split.init({}, ctx)
    insts.append(ProcessorInstance(split, "split/inner"))
    for i, cfg in enumerate(proc_cfgs):
        p = reg.create_processor(cfg["Type"])
        assert p is not None, cfg["Type"]
        assert p.init(cfg, ctx), cfg
        insts.append(ProcessorInstance(p, f"{cfg['Type']}/{i}"))
    return insts


def _run_chain(corpus: bytes, proc_cfgs, columnar: bool
               ) -> PipelineEventGroup:
    prev = models.set_columnar_enabled(columnar)
    try:
        insts = _build_chain(proc_cfgs)
        sb = SourceBuffer(len(corpus) + 128)
        g = PipelineEventGroup(sb)
        g.add_raw_event(1700000001).set_content(sb.copy_string(corpus))
        g.set_tag(b"host", b"equiv-host")
        for inst in insts:
            inst.process([g])
        if not columnar and g.is_columnar() and not g._events:
            # the FlusherInstance boundary of the dict path: sinks get
            # per-event row objects
            g.materialize("sink")
        return g
    finally:
        models.set_columnar_enabled(prev)


def _es_flusher():
    from loongcollector_tpu.flusher.elasticsearch import FlusherElasticsearch
    f = FlusherElasticsearch()
    ok = f.init({"Addresses": ["http://localhost:9200"], "Index": "logs"},
                PluginContext("columnar-equiv"))
    assert ok
    return f


def _loki_flusher():
    from loongcollector_tpu.flusher.loki import FlusherLoki
    f = FlusherLoki()
    ok = f.init({"URL": "http://localhost:3100"},
                PluginContext("columnar-equiv"))
    assert ok
    return f


def sink_payloads(group: PipelineEventGroup) -> dict:
    """Every NDJSON/wire-riding sink family's payload bytes for one
    group — the exact builders the flushers call."""
    out = {}
    out["file/stdout/kafka json"] = JsonSerializer().serialize([group])
    out["blackhole/sls pb"] = bytes(
        SLSEventGroupSerializer().serialize_view([group]))
    out["clickhouse/doris ndjson"] = \
        ndjson_payload([group], ts_key="_timestamp") or b""
    es, loki = _es_flusher(), _loki_flusher()
    try:
        built = es.build_payload([group])
        out["elasticsearch bulk"] = built[0] if built else b""
        built = loki.build_payload([group])
        out["loki push"] = built[0] if built else b""
    finally:
        es.batcher.close()
        loki.batcher.close()
    return out


def main() -> int:
    bad = 0
    for name, corpus, cfgs in _chains():
        chain_bad = 0
        models.reset_churn_stats()
        g_col = _run_chain(corpus, cfgs, columnar=True)
        pay_col = sink_payloads(g_col)
        churn = models.churn_stats()["materialized_events"]
        if churn:
            chain_bad += 1
            print(f"FAIL[{name}] columnar run materialized {churn} events "
                  f"at {models.churn_stats()['by_boundary']} — the fast "
                  "path is not zero-materialization")
        g_dict = _run_chain(corpus, cfgs, columnar=False)
        pay_dict = sink_payloads(g_dict)
        for sink in pay_col:
            a, b = pay_col[sink], pay_dict[sink]
            if bytes(a) != bytes(b):
                chain_bad += 1
                print(f"FAIL[{name}/{sink}] columnar != dict "
                      f"({len(a)} vs {len(b)} bytes)")
                for i, (x, y) in enumerate(zip(bytes(a), bytes(b))):
                    if x != y:
                        print(f"  first diff at byte {i}: "
                              f"{bytes(a)[max(0,i-20):i+20]!r} vs "
                              f"{bytes(b)[max(0,i-20):i+20]!r}")
                        break
        bad += chain_bad
        print(f"{name}: {len(g_col)} events x {len(pay_col)} sink families "
              f"— {'OK' if not chain_bad else f'{chain_bad} FAILURES'} "
              f"(columnar materialized_events={churn})")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
