#!/usr/bin/env python
"""Resource regression harness: CPU% / RSS while ingesting 10 MB/s.

Reference: test/benchmark/local/test_cases/performance_file_to_blackhole_*
+ docs/cn/developer-guide/test/benchmark.md:43-56 — the reference feeds
10 MB/s of 512-byte lines into a file-tail pipeline and records the
agent's CPU max/avg and RAM max/avg via cadvisor.  BASELINE.md rows:
3.40 % CPU / 29 MB RAM (simple), 5.82 % / 29 MB (multiline),
14.20 % / 34 MB (regex).

This harness does the same against OUR agent without docker: it launches
`python -m loongcollector_tpu` as a subprocess, appends 512-byte lines at
the target rate, and samples /proc/<pid>/stat (utime+stime) and VmRSS
once per second.  Scenarios: simple (raw tail -> blackhole), regex
(apache parse), multiline (java stacktrace assembly).

Standalone:  python scripts/resource_bench.py [--duration 30] [--rate 10]
Importable:  run_all(duration_s, rate_mbps) -> {scenario: {...}} —
bench.py embeds a short run into its JSON `extra`.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

APACHE_RE = (r'(\S+) (\S+) (\S+) \[([^\]]+)\] '
             r'"(\S+) (\S+) ([^"]*)" (\d{3}) (\d+)')

_CLK = os.sysconf("SC_CLK_TCK")


def _pipeline_yaml(scenario: str, log_path: str) -> str:
    head = ("inputs:\n"
            "  - Type: input_file\n"
            "    FilePaths:\n"
            f"      - {log_path}\n")
    if scenario == "regex":
        procs = ("processors:\n"
                 "  - Type: processor_parse_regex_tpu\n"
                 "    SourceKey: content\n"
                 f"    Regex: '{APACHE_RE}'\n"
                 "    Keys: [ip, ident, user, time, method, url, protocol,"
                 " status, size]\n")
    elif scenario == "multiline":
        procs = ("processors:\n"
                 "  - Type: processor_split_multiline_log_string_native\n"
                 "    Multiline:\n"
                 "      StartPattern: '\\d{4}-\\d{2}-\\d{2} .*'\n")
    else:
        procs = ""
    return head + procs + "flushers:\n  - Type: flusher_blackhole\n"


def _make_line(scenario: str, i: int, size: int = 512) -> bytes:
    if scenario == "regex":
        base = (f'10.0.{(i >> 8) & 255}.{i & 255} - user{i % 997} '
                f'[10/Oct/2000:13:55:{i % 60:02d} -0700] '
                f'"GET /api/v1/resource/{i} HTTP/1.1" 200 ')
        pad = size - len(base) - 1
        return (base + str(10 ** (pad - 1))).encode()[:size - 1] + b"\n"
    if scenario == "multiline" and i % 4:
        body = f"  at com.example.Cls{i % 89}.method(Cls.java:{i % 997})"
        return (body + " " * (size - len(body) - 1)).encode() + b"\n"
    stamp = f"2024-01-02 03:04:{i % 60:02d} INFO request {i} handled "
    return (stamp + "x" * (size - len(stamp) - 1)).encode() + b"\n"


def _sample(pid: int):
    """(cpu_ticks_total, rss_mb) or None if the process is gone."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            parts = f.read().rsplit(b")", 1)[1].split()
        ticks = int(parts[11]) + int(parts[12])   # utime + stime
        rss_mb = 0.0
        with open(f"/proc/{pid}/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    rss_mb = int(line.split()[1]) / 1024.0
                    break
        return ticks, rss_mb
    except (OSError, IndexError, ValueError):
        return None


def run_scenario(scenario: str, duration_s: float = 30.0,
                 rate_mbps: float = 10.0) -> dict:
    work = tempfile.mkdtemp(prefix=f"resbench_{scenario}_")
    cfg_dir = os.path.join(work, "config")
    os.makedirs(cfg_dir)
    log_path = os.path.join(work, "in.log")
    open(log_path, "wb").close()
    with open(os.path.join(cfg_dir, "bench.yaml"), "w") as f:
        f.write(_pipeline_yaml(scenario, log_path))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "loongcollector_tpu",
         "--config", cfg_dir, "--data-dir", os.path.join(work, "data")],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        time.sleep(4.0)                      # startup + first discovery
        if proc.poll() is not None:
            raise RuntimeError(f"agent died rc={proc.returncode}")
        chunk_lines = max(1, int(rate_mbps * 1e6 / 512 / 10))
        line_no = 0
        # warm-up feed (engine/tier selection happens on first batch)
        with open(log_path, "ab") as sink_f:
            for _ in range(3):
                buf = bytearray()
                for _ in range(chunk_lines):
                    buf += _make_line(scenario, line_no)
                    line_no += 1
                sink_f.write(buf)
                sink_f.flush()
                time.sleep(0.1)
        base = _sample(proc.pid)
        if base is None:
            raise RuntimeError("agent vanished during warm-up")
        cpu_samples, rss_samples = [], []
        t0 = time.monotonic()
        lines_at_t0 = line_no          # warm-up bytes don't count
        last_ticks, last_t = base[0], t0
        next_write = t0
        with open(log_path, "ab") as sink_f:
            while time.monotonic() - t0 < duration_s:
                buf = bytearray()
                for _ in range(chunk_lines):
                    buf += _make_line(scenario, line_no)
                    line_no += 1
                sink_f.write(buf)
                sink_f.flush()
                next_write += 0.1
                sleep = next_write - time.monotonic()
                if sleep > 0:
                    time.sleep(sleep)
                now = time.monotonic()
                if now - last_t >= 1.0:
                    s = _sample(proc.pid)
                    if s is None:
                        raise RuntimeError("agent died mid-bench")
                    ticks, rss = s
                    cpu_samples.append(
                        (ticks - last_ticks) / _CLK / (now - last_t) * 100)
                    rss_samples.append(rss)
                    last_ticks, last_t = ticks, now
        fed_mb = (line_no - lines_at_t0) * 512 / 1e6
        if not cpu_samples:
            raise RuntimeError("bench window too short for samples")
        return {
            "cpu_pct_avg": round(sum(cpu_samples) / len(cpu_samples), 2),
            "cpu_pct_max": round(max(cpu_samples), 2),
            "rss_mb_avg": round(sum(rss_samples) / len(rss_samples), 1),
            "rss_mb_max": round(max(rss_samples), 1),
            "fed_MB": round(fed_mb, 1),
            "rate_MBps": round(fed_mb / (time.monotonic() - t0), 2),
        }
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        shutil.rmtree(work, ignore_errors=True)


def run_all(duration_s: float = 30.0, rate_mbps: float = 10.0) -> dict:
    out = {}
    for scenario in ("simple", "regex", "multiline"):
        out[scenario] = run_scenario(scenario, duration_s, rate_mbps)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--rate", type=float, default=10.0)
    ap.add_argument("--scenario", choices=["simple", "regex", "multiline"])
    args = ap.parse_args()
    if args.scenario:
        res = {args.scenario: run_scenario(args.scenario, args.duration,
                                           args.rate)}
    else:
        res = run_all(args.duration, args.rate)
    print(json.dumps(res, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
