#!/usr/bin/env bash
# Native sanitizer plane (ISSUE 16): build the C++ data plane under
# ASan+UBSan (default) or TSan (--tsan), point the ctypes bridge at the
# instrumented libraries via LOONG_NATIVE_LIB / LOONG_EBPF_DRIVER, and
# drive the native test corpus plus the four native-exercising
# equivalence gates through them.  Any sanitizer report is fatal:
# recovery is compiled out (-fno-sanitize-recover=all) and halt_on_error
# aborts the process, so a clean exit MEANS no reports.
#
# Python loads the instrumented .so into an uninstrumented interpreter,
# which requires the sanitizer runtime preloaded before libc
# (LD_PRELOAD); leak detection stays off because CPython itself holds
# allocations for the process lifetime and would drown the exit report.
#
#   scripts/sanitize.sh            ASan+UBSan: native corpus + gates
#   scripts/sanitize.sh --tsan     TSan variant (native corpus only —
#                                  opt-in, slower, and the gates run the
#                                  same single-threaded entry points)
#   scripts/sanitize.sh --probe    exit 0 iff the toolchain can build
#                                  and preload sanitized libraries
set -euo pipefail
cd "$(dirname "$0")/.."

CXX="${CXX:-g++}"

probe() {
    command -v "$CXX" >/dev/null 2>&1 || return 1
    command -v make >/dev/null 2>&1 || return 1
    local asan
    asan="$("$CXX" -print-file-name=libasan.so 2>/dev/null)" || return 1
    # an unresolved runtime echoes the bare name back
    [ -e "$asan" ] || return 1
    return 0
}

if [ "${1:-}" = "--probe" ]; then
    probe || { echo "sanitize: no usable sanitizer toolchain"; exit 1; }
    echo "sanitize: toolchain OK ($CXX + libasan)"
    exit 0
fi

probe || {
    echo "sanitize: no usable sanitizer toolchain ($CXX/libasan missing)"
    exit 1
}

VARIANT=asan
if [ "${1:-}" = "--tsan" ]; then
    VARIANT=tsan
fi

echo "== sanitize: building native plane ($VARIANT) =="
make -C native "$VARIANT"

BUILD_DIR="$PWD/native/build/$VARIANT"
export LOONG_NATIVE_LIB="$BUILD_DIR/libloongcollector_native.so"
export LOONG_EBPF_DRIVER="$BUILD_DIR/libloong_ebpf_sim.so"
export JAX_PLATFORMS=cpu

if [ "$VARIANT" = tsan ]; then
    RUNTIMES="$("$CXX" -print-file-name=libtsan.so)"
    export TSAN_OPTIONS="halt_on_error=1 report_thread_leaks=0"
else
    RUNTIMES="$("$CXX" -print-file-name=libasan.so)"
    UBSAN_RT="$("$CXX" -print-file-name=libubsan.so)"
    [ -e "$UBSAN_RT" ] && RUNTIMES="$RUNTIMES $UBSAN_RT"
    export ASAN_OPTIONS="detect_leaks=0:halt_on_error=1"
    export UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1"
fi
export LD_PRELOAD="$RUNTIMES"

echo "== sanitize: native test corpus ($VARIANT) =="
python -m pytest tests/test_native.py tests/test_native_t1.py \
    -q -p no:cacheprovider

if [ "$VARIANT" = tsan ]; then
    echo "sanitize OK (tsan)"
    exit 0
fi

# the four equivalence gates cross-check every native entry point
# against the numpy/python substrates — under ASan they double as a
# memory-safety sweep of the exact byte patterns the gates generate
echo "== sanitize: structural-index equivalence =="
python scripts/struct_equivalence.py

echo "== sanitize: fused-DFA equivalence =="
python scripts/fuse_equivalence.py

echo "== sanitize: columnar equivalence =="
python scripts/columnar_equivalence.py

echo "== sanitize: aggregation equivalence =="
python scripts/agg_equivalence.py

echo "sanitize OK (asan+ubsan)"
