#!/usr/bin/env python
"""loongledger overhead smoke gate (wired into scripts/lint.sh).

The loongledger contract (docs/observability.md#event-conservation-ledger)
follows the chaos/trace/prof idiom: with ``LOONG_LEDGER`` off, every hook
— ``ledger.is_on`` and ``ledger.record`` — is one module-global read +
branch.  Same two-layer proof as scripts/trace_overhead.py /
prof_overhead.py, same paired-min method:

1. **Per-hook microbench** — ns/call of the disabled hooks under a
   generous absolute ceiling (a disabled path that allocates, locks or
   formats blows through it immediately).

2. **Synthetic pipeline** — the ledgered hot path (bounded-queue
   push/pop + ProcessorInstance split stage + SLS serialization) timed
   with hooks as shipped (ledger disabled) vs the same hooks
   monkeypatched to bare no-ops, interleaved paired rounds; the gate is
   the MINIMUM paired disabled/baseline ratio (>5% in EVERY round
   fails).  The ledger-enabled time is reported informationally —
   enabling MAY cost, disabling MUST NOT.
"""

import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__file__), ".."))

N_GROUPS = 400
EVENTS_PER_GROUP = 24
REPEATS = 9
MAX_DISABLED_OVER_BASELINE = 1.05      # the 5% gate
MAX_HOOK_NS = 2_000                    # catastrophic-regression ceiling


def bench_hooks():
    from loongcollector_tpu.monitor import ledger
    ledger.disable()
    out = {}
    for label, fn in (("is_on", ledger.is_on),
                      ("record", lambda: ledger.record(
                          "p", ledger.B_INGEST, 1, 64))):
        n = 200_000
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, (time.perf_counter() - t0) / n)
        out[label] = best * 1e9
    return out


def make_runner():
    from loongcollector_tpu.models import PipelineEventGroup, SourceBuffer
    from loongcollector_tpu.pipeline.plugin.instance import ProcessorInstance
    from loongcollector_tpu.pipeline.plugin.interface import PluginContext
    from loongcollector_tpu.pipeline.queue.bounded_queue import \
        BoundedProcessQueue
    from loongcollector_tpu.pipeline.serializer.sls_serializer import \
        SLSEventGroupSerializer
    from loongcollector_tpu.processor.split_log_string import \
        ProcessorSplitLogString
    inst = ProcessorInstance(ProcessorSplitLogString(),
                             "split/ledger_overhead")
    assert inst.init({}, PluginContext("ledger_overhead"))
    ser = SLSEventGroupSerializer()
    line = b"2024-01-02 03:04:05 INFO request handled ok\n"
    data = line * EVENTS_PER_GROUP
    q = BoundedProcessQueue(1, capacity=4, pipeline_name="ledger_overhead")

    def run_timed():
        t0 = time.perf_counter()
        for _ in range(N_GROUPS):
            sb = SourceBuffer(len(data) + 64)
            g = PipelineEventGroup(sb)
            g.add_raw_event(1).set_content(sb.copy_string(data))
            # the ledgered hand-offs: queue admit → pop → stage → payload
            assert q.push(g)
            g = q.pop()
            inst.process([g])
            ser.serialize([g])
            assert len(g) == EVENTS_PER_GROUP
        return time.perf_counter() - t0

    return inst, run_timed


def main() -> int:
    from loongcollector_tpu.monitor import ledger
    hooks = bench_hooks()
    print("disabled hook cost (ns/call): "
          + ", ".join(f"{k}={v:.0f}" for k, v in hooks.items()))
    bad = {k: v for k, v in hooks.items() if v > MAX_HOOK_NS}
    if bad:
        print(f"FAIL: disabled hooks over {MAX_HOOK_NS} ns: {bad}")
        return 1

    import gc
    inst, run_timed = make_runner()
    noop_false = lambda: False                        # noqa: E731
    noop_none = lambda *a, **k: None                  # noqa: E731
    real = (ledger.is_on, ledger.record)

    def set_baseline():
        ledger.disable()
        ledger.is_on = noop_false
        ledger.record = noop_none

    def set_disabled():
        (ledger.is_on, ledger.record) = real
        ledger.disable()

    def set_enabled():
        (ledger.is_on, ledger.record) = real
        ledger.enable()

    # Paired rounds, min ratio across rounds: a REAL disabled-path
    # regression is systematic and survives every pairing; co-tenant CPU
    # steal on a shared core does not (see scripts/trace_overhead.py).
    dis_ratios, en_ratios = [], []
    try:
        run_timed()                                   # warm the path
        for i in range(REPEATS):
            pair = [("baseline", set_baseline), ("disabled", set_disabled)]
            if i % 2:                                 # kill position bias
                pair.reverse()
            times = {}
            for name, setup in pair + [("enabled", set_enabled)]:
                setup()
                gc.collect()
                times[name] = run_timed()
                ledger.disable()
            dis_ratios.append(times["disabled"] / times["baseline"])
            en_ratios.append(times["enabled"] / times["baseline"])
    finally:
        (ledger.is_on, ledger.record) = real
        ledger.disable()
        inst.metrics.mark_deleted()

    ratio = min(dis_ratios)
    print(f"{N_GROUPS}x{EVENTS_PER_GROUP}-event synthetic pipeline, "
          f"{REPEATS} paired rounds: "
          f"disabled/baseline min={ratio:.3f} "
          f"median={sorted(dis_ratios)[len(dis_ratios) // 2]:.3f}  "
          f"enabled/baseline min={min(en_ratios):.3f}")
    if ratio > MAX_DISABLED_OVER_BASELINE:
        print(f"FAIL: disabled-path overhead {(ratio - 1) * 100:.1f}% "
              f"> {(MAX_DISABLED_OVER_BASELINE - 1) * 100:.0f}% in every "
              "round — the disabled ledger must stay one branch per hook")
        return 1
    print("ledger overhead OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
