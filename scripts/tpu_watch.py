#!/usr/bin/env python
"""TPU-tunnel watcher: probe until the axon backend is live, then bench.

The TPU tunnel in this deployment can be down for hours (see
utils/backend.py). The driver only records BENCH_r{N}.json at round end, so
a window of TPU availability mid-round would otherwise be wasted. This
watcher probes in a subprocess (a wedged tunnel HANGS in-process), and on
the first live probe runs bench.py on the real device, persisting the JSON
line to TPU_BENCH_LATEST.json so ANY availability window yields a real
hardware number (VERDICT r2 item #1).

Usage: python scripts/tpu_watch.py [--interval SECS] [--once]
Exits 0 after one successful TPU bench; exits 3 on --once with no TPU.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = ("import jax, jax.numpy as jnp;"
         "d = jax.devices()[0];"
         "jnp.zeros(8).block_until_ready();"
         "print('PLATFORM:', d.platform)")


def probe(timeout=90.0):
    """Returns the live platform name ('tpu'/'axon'/'cpu'...) or None."""
    try:
        r = subprocess.run([sys.executable, "-c", PROBE],
                           capture_output=True, timeout=timeout, text=True)
    except Exception:
        return None
    if r.returncode != 0:
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM:"):
            return line.split(":", 1)[1].strip()
    return None


def run_bench(log):
    """Run bench.py on the (now live) default backend; persist the line."""
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           capture_output=True, timeout=1800, text=True,
                           cwd=REPO)
    except subprocess.TimeoutExpired:
        log(f"bench TIMED OUT after {time.time()-t0:.0f}s")
        return False
    log(f"bench rc={r.returncode} in {time.time()-t0:.0f}s")
    if r.stderr:
        log("stderr: " + r.stderr[-3000:])
    line = None
    for ln in r.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = ln
    if r.returncode != 0 or line is None:
        return False
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        log("unparseable bench line: " + line[:500])
        return False
    if doc.get("extra", {}).get("device_degraded"):
        log("bench ran but DEGRADED (tunnel died mid-run?)")
        return False
    doc["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    out = os.path.join(REPO, "TPU_BENCH_LATEST.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    log(f"SUCCESS: wrote {out}: value={doc['value']} {doc['unit']} "
        f"vs_baseline={doc['vs_baseline']} device={doc['extra'].get('device')}")
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=180.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()
    logpath = os.path.join(REPO, "scripts", "tpu_watch.log")

    def log(msg):
        stamp = time.strftime("%H:%M:%S")
        with open(logpath, "a") as f:
            f.write(f"[{stamp}] {msg}\n")
        print(f"[{stamp}] {msg}", flush=True)

    log(f"watcher started (pid {os.getpid()}, interval {args.interval}s)")
    while True:
        plat = probe()
        if plat is None:
            log("probe: tunnel dead/hung")
        elif plat == "cpu":
            log("probe: live but CPU-only (no TPU attached)")
        else:
            log(f"probe: LIVE platform={plat} — running bench")
            if run_bench(log):
                return 0
            log("bench failed despite live probe; will retry")
        if args.once:
            return 3
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
