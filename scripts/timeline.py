#!/usr/bin/env python
"""Dump the unified host/device execution timeline (loongxprof).

Two modes:

  * ``--url http://127.0.0.1:9400`` (or ``--port 9400``) — fetch
    ``/debug/timeline`` from a running agent's exposition endpoint and
    write it to ``--out`` (default ``timeline.json``).  Load the file in
    Perfetto (ui.perfetto.dev) or chrome://tracing.

  * ``--demo`` — no running agent: enable loongtrace + loongxprof in
    process, run a short seeded synthetic dispatch storm through a
    private DevicePlane, and dump ITS timeline.  The offline smoke test
    for the export path, and a worked example of what the correlated
    document looks like.

``--canonical`` writes the canonicalize() reduction instead (the
timing-independent structure two runs of the same seed must agree on).
"""

import argparse
import json
import sys
import urllib.request

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__file__), ".."))


def fetch(url: str) -> dict:
    with urllib.request.urlopen(url + "/debug/timeline", timeout=10) as r:
        return json.loads(r.read().decode("utf-8"))


def demo(seed: int) -> dict:
    import numpy as np
    from loongcollector_tpu import trace
    from loongcollector_tpu.ops import xprof
    from loongcollector_tpu.ops.device_plane import (
        DevicePlane, LatencyInjectedKernel)
    from loongcollector_tpu.trace.export import chrome_trace
    from loongcollector_tpu.trace.tracer import TraceConfig

    rng = np.random.default_rng(seed)
    trace.enable(TraceConfig(seed=seed))
    xprof.enable()
    try:
        plane = DevicePlane(budget_bytes=1 << 20)
        kernel = LatencyInjectedKernel(lambda a: (a,), rtt_s=0.002)
        for i in range(8):
            rows = rng.integers(0, 255, size=(4, 64), dtype=np.uint8)
            with trace.start_span("device.roundtrip"):
                fut = plane.submit(kernel, (rows,), rows.nbytes)
                xprof.note_dispatch(fut, "demo", f"{rows.shape[0]}x"
                                    f"{rows.shape[1]}")
                fut.result()
        tracer = trace.active_tracer()
        timeline = xprof.active_timeline()
        return chrome_trace(tracer=tracer, timeline=timeline)
    finally:
        xprof.disable()
        trace.disable()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", help="agent exposition base url")
    ap.add_argument("--port", type=int,
                    help="shorthand for --url http://127.0.0.1:PORT")
    ap.add_argument("--demo", action="store_true",
                    help="run a synthetic seeded storm in-process")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--out", default="timeline.json",
                    help="output path ('-' for stdout)")
    ap.add_argument("--canonical", action="store_true",
                    help="write the canonicalize() structure bytes instead")
    args = ap.parse_args()

    if args.demo:
        doc = demo(args.seed)
    else:
        url = args.url or (args.port and f"http://127.0.0.1:{args.port}")
        if not url:
            ap.error("one of --url/--port/--demo is required")
        doc = fetch(url)

    if args.canonical:
        from loongcollector_tpu.trace.export import canonicalize
        body = canonicalize(doc)
    else:
        body = (json.dumps(doc, sort_keys=True) + "\n").encode("utf-8")

    if args.out == "-":
        sys.stdout.buffer.write(body)
    else:
        with open(args.out, "wb") as f:
            f.write(body)
        n = len(doc.get("traceEvents", []))
        print(f"wrote {args.out}: {n} trace events ({len(body)} bytes)"
              + ("" if args.canonical
                 else " — load in ui.perfetto.dev or chrome://tracing"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
