/* Simulation eBPF driver: an out-of-tree-shaped implementation of the
 * loong_ebpf_driver ABI (ebpf_driver_abi.h), loaded by the collector via
 * dlopen exactly like a real kernel driver would be.
 *
 * Reference analogue: core/ebpf/driver/ — the reference compiles its BPF
 * wrapper layer into a separate library the agent dlopens
 * (EBPFAdapter.cpp:149-231).  In unprivileged containers no kernel BPF can
 * load, so this driver substitutes a deterministic event source: events
 * arrive via inject() (tests, replay harnesses) and are delivered to the
 * registered callback on a dedicated poll thread — preserving the real
 * driver's threading contract (callbacks never run on the injecting
 * thread, just as perf-buffer callbacks never run on the producing CPU's
 * context).
 */

#include "ebpf_driver_abi.h"

#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>

namespace {

struct SourceState {
    loong_ebpf_cb cb = nullptr;
    void *user = nullptr;
    bool running = false;
    bool suspended = false;
};

/* Deliberately LEAKED singletons: the poll thread is detached and may
 * still be blocked on the condvar when the process exits; running static
 * destructors under it (destroying a condvar in use) is UB and hangs
 * interpreter shutdown.  Process-lifetime objects are never destroyed. */
std::mutex &g_mu = *new std::mutex;
std::condition_variable &g_cv = *new std::condition_variable;
SourceState g_sources[LOONG_EBPF_SOURCE_COUNT];
std::deque<loong_ebpf_event_t> &g_queue =
    *new std::deque<loong_ebpf_event_t>;  /* the simulated perf buffer */
bool g_poll_running = false;
bool g_shutdown = false;

void poll_loop() {
    std::unique_lock<std::mutex> lk(g_mu);
    while (!g_shutdown) {
        g_cv.wait(lk, [] { return g_shutdown || !g_queue.empty(); });
        while (!g_queue.empty()) {
            loong_ebpf_event_t ev = g_queue.front();
            g_queue.pop_front();
            if (ev.source >= LOONG_EBPF_SOURCE_COUNT) continue;
            SourceState &st = g_sources[ev.source];
            if (!st.running || st.suspended || !st.cb) continue;
            loong_ebpf_cb cb = st.cb;
            void *user = st.user;
            lk.unlock();              /* never deliver under the lock */
            cb(&ev, user);
            lk.lock();
        }
    }
}

void ensure_poll_thread() {
    if (!g_poll_running) {
        g_shutdown = false;
        std::thread(poll_loop).detach();  /* process-lifetime perf poller */
        g_poll_running = true;
    }
}

int drv_start(uint32_t source, loong_ebpf_cb cb, void *user) {
    if (source >= LOONG_EBPF_SOURCE_COUNT || cb == nullptr)
        return LOONG_EBPF_EINVAL;
    std::lock_guard<std::mutex> lk(g_mu);
    SourceState &st = g_sources[source];
    if (st.running) return LOONG_EBPF_ESTATE;
    st.cb = cb;
    st.user = user;
    st.running = true;
    st.suspended = false;
    ensure_poll_thread();
    return LOONG_EBPF_OK;
}

int drv_stop(uint32_t source) {
    if (source >= LOONG_EBPF_SOURCE_COUNT) return LOONG_EBPF_EINVAL;
    std::lock_guard<std::mutex> lk(g_mu);
    SourceState &st = g_sources[source];
    if (!st.running) return LOONG_EBPF_ESTATE;
    st.running = false;
    st.cb = nullptr;
    st.user = nullptr;
    return LOONG_EBPF_OK;
}

int drv_suspend(uint32_t source) {
    if (source >= LOONG_EBPF_SOURCE_COUNT) return LOONG_EBPF_EINVAL;
    std::lock_guard<std::mutex> lk(g_mu);
    if (!g_sources[source].running) return LOONG_EBPF_ESTATE;
    g_sources[source].suspended = true;
    return LOONG_EBPF_OK;
}

int drv_resume(uint32_t source) {
    if (source >= LOONG_EBPF_SOURCE_COUNT) return LOONG_EBPF_EINVAL;
    std::lock_guard<std::mutex> lk(g_mu);
    if (!g_sources[source].running) return LOONG_EBPF_ESTATE;
    g_sources[source].suspended = false;
    return LOONG_EBPF_OK;
}

int drv_inject(const loong_ebpf_event_t *ev) {
    if (ev == nullptr || ev->source >= LOONG_EBPF_SOURCE_COUNT)
        return LOONG_EBPF_EINVAL;
    if (ev->payload_len > LOONG_EBPF_PAYLOAD_MAX ||
        ev->stack_depth > LOONG_EBPF_STACK_DEPTH)
        return LOONG_EBPF_EINVAL;
    std::lock_guard<std::mutex> lk(g_mu);
    g_queue.push_back(*ev);
    g_cv.notify_one();
    return LOONG_EBPF_OK;
}

const loong_ebpf_driver_t g_driver = {
    LOONG_EBPF_ABI_VERSION,
    (uint32_t)sizeof(loong_ebpf_event_t),
    drv_start,
    drv_stop,
    drv_suspend,
    drv_resume,
    drv_inject,
};

}  // namespace

extern "C" const loong_ebpf_driver_t *loong_ebpf_driver_get(void) {
    return &g_driver;
}
