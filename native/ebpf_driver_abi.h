/* Versioned C ABI between the collector and an eBPF driver library.
 *
 * Reference boundary: core/ebpf/EBPFAdapter.cpp:149-231 dlopens the driver
 * (BPF program loading + perf-buffer polling live there) and talks to it
 * through a fixed symbol set; core/collection_pipeline/plugin/creator/
 * CProcessor.h is the pattern for VERSIONED out-of-tree plugin ABIs.
 *
 * The collector never links the driver: it dlopens a .so exposing ONE
 * symbol, loong_ebpf_driver_get(), returning a vtable whose first two
 * fields pin the ABI version and the event-struct size.  Any real kernel
 * driver (coolbpf-style) and the in-tree simulation implement the same
 * table, so "eBPF support" survives contact with a real driver.
 *
 * Layout rules: fixed-size POD only, 8-byte alignment, no pointers inside
 * the event (the event must be copyable across the boundary and, later,
 * straight out of a perf-buffer mmap).
 */

#ifndef LOONG_EBPF_DRIVER_ABI_H
#define LOONG_EBPF_DRIVER_ABI_H

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* v2: +ppid +ktime on the event (process-tree cache keys events by
 * (pid, ktime) and links children to parents — ProcessCacheManager.h:70
 * AttachProcessData semantics need both on every kernel event) */
#define LOONG_EBPF_ABI_VERSION 2u

/* event sources (mirrors the collector's EventSource enum) */
enum loong_ebpf_source {
    LOONG_EBPF_NETWORK_OBSERVE  = 0,
    LOONG_EBPF_PROCESS_SECURITY = 1,
    LOONG_EBPF_FILE_SECURITY    = 2,
    LOONG_EBPF_NETWORK_SECURITY = 3,
    LOONG_EBPF_CPU_PROFILING    = 4,
    LOONG_EBPF_SOURCE_COUNT     = 5
};

enum loong_ebpf_direction {
    LOONG_EBPF_DIR_NONE    = 0,
    LOONG_EBPF_DIR_INGRESS = 1,
    LOONG_EBPF_DIR_EGRESS  = 2
};

#define LOONG_EBPF_CALLNAME_MAX 32
#define LOONG_EBPF_PATH_MAX     128
#define LOONG_EBPF_ADDR_MAX     64
#define LOONG_EBPF_PAYLOAD_MAX  4096
#define LOONG_EBPF_STACK_DEPTH  32
#define LOONG_EBPF_FRAME_MAX    96

/* one raw kernel event — what a perf buffer would deliver */
typedef struct loong_ebpf_event {
    uint64_t timestamp_ns;
    uint32_t source;                       /* enum loong_ebpf_source   */
    int32_t  pid;
    int32_t  fd;                           /* -1 when not applicable   */
    uint32_t flags;
    uint16_t direction;                    /* enum loong_ebpf_direction */
    uint16_t stack_depth;                  /* used frames              */
    uint32_t payload_len;                  /* used bytes of payload    */
    int32_t  ppid;                         /* parent pid (-1 unknown)  */
    uint32_t reserved0;                    /* alignment / future use   */
    uint64_t ktime;                        /* proc start ktime (id key) */
    char     call_name[LOONG_EBPF_CALLNAME_MAX];   /* NUL-terminated   */
    char     path[LOONG_EBPF_PATH_MAX];
    char     local_addr[LOONG_EBPF_ADDR_MAX];
    char     remote_addr[LOONG_EBPF_ADDR_MAX];
    uint8_t  payload[LOONG_EBPF_PAYLOAD_MAX];
    char     stack[LOONG_EBPF_STACK_DEPTH][LOONG_EBPF_FRAME_MAX];
} loong_ebpf_event_t;

/* delivered on the driver's poll thread; the collector must not block */
typedef void (*loong_ebpf_cb)(const loong_ebpf_event_t *ev, void *user);

/* return codes */
#define LOONG_EBPF_OK        0
#define LOONG_EBPF_EINVAL   -1
#define LOONG_EBPF_ESTATE   -2

typedef struct loong_ebpf_driver {
    uint32_t abi_version;     /* must equal LOONG_EBPF_ABI_VERSION      */
    uint32_t event_size;      /* must equal sizeof(loong_ebpf_event_t)  */
    int (*start)(uint32_t source, loong_ebpf_cb cb, void *user);
    int (*stop)(uint32_t source);
    int (*suspend)(uint32_t source);
    int (*resume)(uint32_t source);
    /* simulation/test hook: inject one event as if read from the kernel;
     * a real kernel driver returns LOONG_EBPF_EINVAL here */
    int (*inject)(const loong_ebpf_event_t *ev);
} loong_ebpf_driver_t;

/* the ONE exported symbol */
const loong_ebpf_driver_t *loong_ebpf_driver_get(void);

#ifdef __cplusplus
}
#endif

#endif /* LOONG_EBPF_DRIVER_ABI_H */
