// Native host-side data plane for loongcollector_tpu.
//
// The reference implements these paths in C++ (SURVEY.md §2.1/§2.3):
//   - chunk → line spans         (LogFileReader / ProcessorSplitLogString)
//   - arena → fixed device rows  (the TPU batch staging copy)
//   - columnar spans → SLS protobuf wire bytes
//     (hand-rolled LogGroupSerializer, core/protobuf/sls/)
//
// Python loads this via ctypes (loongcollector_tpu/native.py) and falls back
// to numpy/pure-Python implementations when the library is absent.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Line splitting: returns number of line spans written.
// Keeps empty interior lines; drops the empty tail after a trailing sep.
// out_offsets/out_lengths must hold at least (count of sep)+1 entries.
// ---------------------------------------------------------------------------
int64_t lct_split_lines(const uint8_t* data, int64_t len, uint8_t sep,
                        int64_t base_offset, int32_t* out_offsets,
                        int32_t* out_lengths) {
    int64_t n = 0;
    int64_t start = 0;
    const uint8_t* p = data;
    while (start < len) {
        const uint8_t* hit =
            static_cast<const uint8_t*>(memchr(p + start, sep, len - start));
        int64_t end = hit ? (hit - p) : len;
        out_offsets[n] = static_cast<int32_t>(base_offset + start);
        out_lengths[n] = static_cast<int32_t>(end - start);
        ++n;
        start = end + 1;
    }
    // interior empty lines between consecutive separators
    // (handled naturally: start==end gives length 0)
    return n;
}

// ---------------------------------------------------------------------------
// Row packing: gather event byte ranges into a zero-padded [B, L] matrix.
// Rows beyond n are zeroed by the caller (numpy allocates zeroed).
// ---------------------------------------------------------------------------
void lct_pack_rows(const uint8_t* arena, int64_t arena_len,
                   const int64_t* offsets, const int32_t* lengths, int64_t n,
                   int64_t L, uint8_t* out_rows) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t off = offsets[i];
        int64_t len = lengths[i];
        if (len < 0) len = 0;  // absent field spans (-1) pack as empty rows
        if (len > L) len = L;
        if (off < 0 || off >= arena_len) {
            len = 0;
        } else if (off + len > arena_len) {
            len = arena_len - off;
        }
        uint8_t* dst = out_rows + i * L;
        if (len > 0) memcpy(dst, arena + off, static_cast<size_t>(len));
        if (len < L) memset(dst + len, 0, static_cast<size_t>(L - len));
    }
}

// ---------------------------------------------------------------------------
// SLS LogGroup wire serialization from columnar spans.
//
// Wire schema (public sls_logs.proto):
//   Log      { uint32 Time = 1; repeated Content Contents = 2; }
//   Content  { string Key = 1; string Value = 2; }
//   LogGroup { repeated Log Logs = 1; ... }
//
// Inputs: shared arena; per-event timestamps; F fields, each with a key
// (concatenated in keys_blob with key_lens) and per-event (offset,len)
// spans (len < 0 ⇒ absent).
// Returns bytes written, or -(needed) if out_cap is too small (caller
// reallocates and retries; needed is exact).
// ---------------------------------------------------------------------------

static inline int varint_size(uint64_t v) {
    int s = 1;
    while (v >= 0x80) { v >>= 7; ++s; }
    return s;
}

static inline uint8_t* put_varint(uint8_t* p, uint64_t v) {
    while (v >= 0x80) { *p++ = static_cast<uint8_t>(v) | 0x80; v >>= 7; }
    *p++ = static_cast<uint8_t>(v);
    return p;
}

int64_t lct_sls_serialize(const uint8_t* arena, int64_t arena_len,
                          const int64_t* timestamps, int64_t n,
                          int64_t F,
                          const uint8_t* keys_blob, const int32_t* key_lens,
                          const int32_t* field_offs,  // [F * n]
                          const int32_t* field_lens,  // [F * n]
                          uint8_t* out, int64_t out_cap) {
    // key prefix offsets into keys_blob
    int64_t key_starts[64];
    if (F > 64) return -1;
    int64_t acc = 0;
    for (int64_t f = 0; f < F; ++f) { key_starts[f] = acc; acc += key_lens[f]; }

    // a span is emitted iff it passes BOTH the absence and bounds checks —
    // the predicate must be identical in the size and write passes or the
    // length prefixes desynchronise from the written bytes
    auto span_ok = [&](int64_t f, int64_t i) -> bool {
        int32_t vlen = field_lens[f * n + i];
        if (vlen < 0) return false;
        int32_t voff = field_offs[f * n + i];
        return voff >= 0 && static_cast<int64_t>(voff) + vlen <= arena_len;
    };

    // pass 1: size
    int64_t total = 0;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t ts = static_cast<uint64_t>(timestamps[i]) & 0xFFFFFFFFu;
        int64_t body = 1 + varint_size(ts);
        for (int64_t f = 0; f < F; ++f) {
            if (!span_ok(f, i)) continue;
            int32_t vlen = field_lens[f * n + i];
            int32_t klen = key_lens[f];
            int64_t content = 1 + varint_size(klen) + klen +
                              1 + varint_size(vlen) + vlen;
            body += 1 + varint_size(content) + content;
        }
        total += 1 + varint_size(body) + body;
    }
    if (total > out_cap) return -total;

    // pass 2: write
    uint8_t* p = out;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t ts = static_cast<uint64_t>(timestamps[i]) & 0xFFFFFFFFu;
        int64_t body = 1 + varint_size(ts);
        for (int64_t f = 0; f < F; ++f) {
            if (!span_ok(f, i)) continue;
            int32_t vlen = field_lens[f * n + i];
            int32_t klen = key_lens[f];
            int64_t content = 1 + varint_size(klen) + klen +
                              1 + varint_size(vlen) + vlen;
            body += 1 + varint_size(content) + content;
        }
        *p++ = 0x0a;                       // LogGroup.Logs
        p = put_varint(p, body);
        *p++ = 0x08;                       // Log.Time
        p = put_varint(p, ts);
        for (int64_t f = 0; f < F; ++f) {
            if (!span_ok(f, i)) continue;
            int32_t vlen = field_lens[f * n + i];
            int32_t voff = field_offs[f * n + i];
            int32_t klen = key_lens[f];
            int64_t content = 1 + varint_size(klen) + klen +
                              1 + varint_size(vlen) + vlen;
            *p++ = 0x12;                   // Log.Contents
            p = put_varint(p, content);
            *p++ = 0x0a;                   // Content.Key
            p = put_varint(p, klen);
            memcpy(p, keys_blob + key_starts[f], klen);
            p += klen;
            *p++ = 0x12;                   // Content.Value
            p = put_varint(p, vlen);
            memcpy(p, arena + voff, vlen);
            p += vlen;
        }
    }
    return p - out;
}

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) — required by Kafka record-batch v2 framing.
// Table-driven; table built on first use.
// ---------------------------------------------------------------------------
static uint32_t crc32c_table[256];
static bool crc32c_ready = false;

static void crc32c_init() {
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t crc = i;
        for (int j = 0; j < 8; ++j)
            crc = (crc >> 1) ^ (0x82F63B78u & (~(crc & 1) + 1));
        crc32c_table[i] = crc;
    }
    crc32c_ready = true;
}

uint32_t lct_crc32c(const uint8_t* data, int64_t len, uint32_t seed) {
    if (!crc32c_ready) crc32c_init();
    uint32_t crc = seed ^ 0xFFFFFFFFu;
    for (int64_t i = 0; i < len; ++i)
        crc = (crc >> 8) ^ crc32c_table[(crc ^ data[i]) & 0xFF];
    return crc ^ 0xFFFFFFFFu;
}

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// Columnar JSON field extraction for flat-schema log events.
//
// For each event (a JSON object), extracts the values of F known keys as
// (offset, len) spans into the arena — zero copies:
//   * strings WITHOUT escapes  → span of the content between the quotes
//   * numbers / true/false/null → span of the raw token
//   * nested objects/arrays     → span of the raw JSON slice
// Events that don't fit the fast path (escaped strings, unknown keys,
// malformed JSON) get fallback_mask=1 and are handled by the host.
// out_offs/out_lens are [F * n] (field-major), len -1 = absent.
// ok[i]=1 iff the event parsed as an object on the fast path.
// ---------------------------------------------------------------------------

static inline int64_t jskip_ws(const uint8_t* a, int64_t p, int64_t end) {
    while (p < end && (a[p] == ' ' || a[p] == '\t' || a[p] == '\n' ||
                       a[p] == '\r'))
        ++p;
    return p;
}

// scan a string starting AFTER the opening quote; returns position of the
// closing quote or -1; sets *had_escape
static inline int64_t jscan_string(const uint8_t* a, int64_t p, int64_t end,
                                   bool* had_escape) {
    while (p < end) {
        uint8_t c = a[p];
        if (c == '\\') { *had_escape = true; p += 2; continue; }
        if (c == '"') return p;
        if (c < 0x20) { *had_escape = true; ++p; continue; }  // strict JSON:
        // raw control chars are invalid — flag so the event falls back to
        // the host parser, keeping both paths' accept/reject identical
        ++p;
    }
    return -1;
}

// strict JSON scalar token: number | true | false | null
static bool json_scalar_valid(const uint8_t* t, int64_t n) {
    if (n == 4 && memcmp(t, "true", 4) == 0) return true;
    if (n == 4 && memcmp(t, "null", 4) == 0) return true;
    if (n == 5 && memcmp(t, "false", 5) == 0) return true;
    int64_t i = 0;
    if (i < n && t[i] == '-') ++i;
    if (i >= n) return false;
    if (t[i] == '0') { ++i; }
    else if (t[i] >= '1' && t[i] <= '9') {
        while (i < n && t[i] >= '0' && t[i] <= '9') ++i;
    } else return false;
    if (i < n && t[i] == '.') {
        ++i;
        if (i >= n || t[i] < '0' || t[i] > '9') return false;
        while (i < n && t[i] >= '0' && t[i] <= '9') ++i;
    }
    if (i < n && (t[i] == 'e' || t[i] == 'E')) {
        ++i;
        if (i < n && (t[i] == '+' || t[i] == '-')) ++i;
        if (i >= n || t[i] < '0' || t[i] > '9') return false;
        while (i < n && t[i] >= '0' && t[i] <= '9') ++i;
    }
    return i == n;
}

void lct_json_extract(const uint8_t* arena, int64_t arena_len,
                      const int64_t* offsets, const int32_t* lengths,
                      int64_t n,
                      const uint8_t* keys_blob, const int32_t* key_lens,
                      int64_t F,
                      int32_t* out_offs, int32_t* out_lens,
                      uint8_t* ok, uint8_t* fallback_mask) {
    int64_t key_starts[128];
    if (F > 128) F = 128;
    {
        int64_t acc = 0;
        for (int64_t f = 0; f < F; ++f) { key_starts[f] = acc; acc += key_lens[f]; }
    }
    for (int64_t f = 0; f < F; ++f)
        for (int64_t i = 0; i < n; ++i) out_lens[f * n + i] = -1;

    for (int64_t i = 0; i < n; ++i) {
        ok[i] = 0;
        fallback_mask[i] = 0;
        int64_t p = offsets[i];
        int64_t end = p + lengths[i];
        if (p < 0 || end > arena_len) { fallback_mask[i] = 1; continue; }
        p = jskip_ws(arena, p, end);
        if (p >= end || arena[p] != '{') { fallback_mask[i] = 1; continue; }
        ++p;
        bool bad = false, fellback = false;
        p = jskip_ws(arena, p, end);
        if (p < end && arena[p] == '}') {
            // empty object: still only whitespace may follow
            int64_t q = jskip_ws(arena, p + 1, end);
            if (q == end) ok[i] = 1; else fallback_mask[i] = 1;
            continue;
        }
        while (p < end) {
            p = jskip_ws(arena, p, end);
            if (p >= end || arena[p] != '"') { bad = true; break; }
            bool kesc = false;
            int64_t kstart = p + 1;
            int64_t kq = jscan_string(arena, kstart, end, &kesc);
            if (kq < 0 || kesc) { fellback = true; break; }
            int64_t klen = kq - kstart;
            p = jskip_ws(arena, kq + 1, end);
            if (p >= end || arena[p] != ':') { bad = true; break; }
            p = jskip_ws(arena, p + 1, end);
            if (p >= end) { bad = true; break; }
            int64_t voff, vlen;
            uint8_t c = arena[p];
            if (c == '"') {
                bool vesc = false;
                int64_t vstart = p + 1;
                int64_t vq = jscan_string(arena, vstart, end, &vesc);
                if (vq < 0) { bad = true; break; }
                if (vesc) { fellback = true; break; }
                voff = vstart; vlen = vq - vstart;
                p = vq + 1;
            } else if (c == '{' || c == '[') {
                // bracket stack so mismatched nesting ({]}) is rejected
                uint8_t stack[64];
                int depth = 0;
                int64_t q = p;
                bool nested_bad = false;
                while (q < end) {
                    uint8_t d = arena[q];
                    if (d == '"') {
                        bool e2 = false;
                        int64_t sq = jscan_string(arena, q + 1, end, &e2);
                        if (sq < 0) { nested_bad = true; break; }
                        q = sq + 1;
                        continue;
                    }
                    if (d == '{' || d == '[') {
                        if (depth >= 64) { nested_bad = true; break; }
                        stack[depth++] = d;
                    } else if (d == '}' || d == ']') {
                        uint8_t want = (d == '}') ? '{' : '[';
                        if (depth == 0 || stack[depth - 1] != want) {
                            nested_bad = true;
                            break;
                        }
                        if (--depth == 0) { ++q; break; }
                    }
                    ++q;
                }
                if (nested_bad || depth != 0) { bad = true; break; }
                voff = p; vlen = q - p;
                p = q;
            } else {
                // number / true / false / null: scan then validate the token
                int64_t q = p;
                while (q < end && arena[q] != ',' && arena[q] != '}' &&
                       arena[q] != ' ' && arena[q] != '\t' &&
                       arena[q] != '\n' && arena[q] != '\r')
                    ++q;
                voff = p; vlen = q - p;
                if (vlen == 0 || !json_scalar_valid(arena + voff, vlen)) {
                    bad = true;
                    break;
                }
                p = q;
            }
            // match against known keys
            bool known = false;
            for (int64_t f = 0; f < F; ++f) {
                if (key_lens[f] == klen &&
                    memcmp(keys_blob + key_starts[f], arena + kstart,
                           static_cast<size_t>(klen)) == 0) {
                    out_offs[f * n + i] = static_cast<int32_t>(voff);
                    out_lens[f * n + i] = static_cast<int32_t>(vlen);
                    known = true;
                    break;
                }
            }
            if (!known) { fellback = true; break; }
            p = jskip_ws(arena, p, end);
            if (p < end && arena[p] == ',') { ++p; continue; }
            if (p < end && arena[p] == '}') {
                p = jskip_ws(arena, p + 1, end);
                if (p == end) ok[i] = 1;
                else bad = true;
                break;
            }
            bad = true;
            break;
        }
        if (fellback || bad) {
            fallback_mask[i] = 1;
            ok[i] = 0;
            for (int64_t f = 0; f < F; ++f) out_lens[f * n + i] = -1;
        }
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Block codecs: LZ4 block + Snappy block, written to the PUBLIC formats
// (lz4 block spec; google/snappy format description). The reference links
// liblz4 (core/common/compression/Lz4Compressor.cpp) — this image has no
// lz4/snappy Python modules, and SLS's DEFAULT codec is LZ4
// (FlusherSLS.h:124-159) while Prometheus remote-write REQUIRES snappy,
// so the codecs live here behind ctypes.
// ---------------------------------------------------------------------------
extern "C" {

int64_t lct_lz4_bound(int64_t n) { return n + n / 255 + 16; }

int64_t lct_lz4_compress(const uint8_t* src, int64_t n,
                         uint8_t* dst, int64_t cap) {
    if (n < 0) return -1;
    if (n == 0) return 0;
    enum { HB = 16 };
    static thread_local uint32_t htab[1u << HB];
    memset(htab, 0, sizeof(htab));
    auto hash = [](uint32_t v) { return (v * 2654435761u) >> (32 - HB); };
    auto rd32 = [&](int64_t p) {
        uint32_t v; memcpy(&v, src + p, 4); return v;
    };
    int64_t ip = 0, anchor = 0, op = 0;
    const int64_t mflimit = n - 12;   // spec: no match may start after this
    const int64_t matchlimit = n - 5; // spec: last 5 bytes are literals
    while (ip < mflimit) {
        uint32_t h = hash(rd32(ip));
        int64_t ref = (int64_t)htab[h] - 1;
        htab[h] = (uint32_t)(ip + 1);
        if (ref < 0 || ip - ref > 65535 || rd32(ref) != rd32(ip)) {
            ip++;
            continue;
        }
        int64_t mlen = 4;
        while (ip + mlen < matchlimit && src[ref + mlen] == src[ip + mlen])
            mlen++;
        int64_t litlen = ip - anchor;
        if (op + litlen + litlen / 255 + mlen / 255 + 12 > cap) return -1;
        uint8_t* tok = dst + op++;
        if (litlen >= 15) {
            *tok = 0xF0;
            int64_t rest = litlen - 15;
            while (rest >= 255) { dst[op++] = 255; rest -= 255; }
            dst[op++] = (uint8_t)rest;
        } else {
            *tok = (uint8_t)(litlen << 4);
        }
        memcpy(dst + op, src + anchor, litlen);
        op += litlen;
        uint16_t off = (uint16_t)(ip - ref);
        dst[op++] = off & 0xFF;
        dst[op++] = off >> 8;
        int64_t mrem = mlen - 4;
        if (mrem >= 15) {
            *tok |= 0x0F;
            mrem -= 15;
            while (mrem >= 255) { dst[op++] = 255; mrem -= 255; }
            dst[op++] = (uint8_t)mrem;
        } else {
            *tok |= (uint8_t)mrem;
        }
        ip += mlen;
        anchor = ip;
    }
    int64_t litlen = n - anchor;
    if (op + litlen + litlen / 255 + 2 > cap) return -1;
    uint8_t* tok = dst + op++;
    if (litlen >= 15) {
        *tok = 0xF0;
        int64_t rest = litlen - 15;
        while (rest >= 255) { dst[op++] = 255; rest -= 255; }
        dst[op++] = (uint8_t)rest;
    } else {
        *tok = (uint8_t)(litlen << 4);
    }
    memcpy(dst + op, src + anchor, litlen);
    op += litlen;
    return op;
}

int64_t lct_lz4_decompress(const uint8_t* src, int64_t n,
                           uint8_t* dst, int64_t cap) {
    int64_t ip = 0, op = 0;
    while (ip < n) {
        uint8_t tok = src[ip++];
        int64_t litlen = tok >> 4;
        if (litlen == 15) {
            uint8_t b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                litlen += b;
            } while (b == 255);
        }
        if (ip + litlen > n || op + litlen > cap) return -1;
        memcpy(dst + op, src + ip, litlen);
        ip += litlen;
        op += litlen;
        if (ip >= n) break;  // last sequence has no match
        if (ip + 2 > n) return -1;
        int64_t off = src[ip] | (src[ip + 1] << 8);
        ip += 2;
        if (off == 0 || off > op) return -1;
        int64_t mlen = (tok & 0x0F);
        if (mlen == 15) {
            uint8_t b;
            do {
                if (ip >= n) return -1;
                b = src[ip++];
                mlen += b;
            } while (b == 255);
        }
        mlen += 4;
        if (op + mlen > cap) return -1;
        // overlapping copy must run byte-wise
        for (int64_t i = 0; i < mlen; i++) dst[op + i] = dst[op + i - off];
        op += mlen;
    }
    return op;
}

int64_t lct_snappy_bound(int64_t n) { return 32 + n + n / 6; }

int64_t lct_snappy_compress(const uint8_t* src, int64_t n,
                            uint8_t* dst, int64_t cap) {
    if (n < 0) return -1;
    int64_t op = 0;
    // preamble: uncompressed length varint
    uint64_t v = (uint64_t)n;
    while (v >= 0x80) {
        if (op >= cap) return -1;
        dst[op++] = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    if (op >= cap) return -1;
    dst[op++] = (uint8_t)v;
    auto emit_literal = [&](int64_t from, int64_t len) -> bool {
        while (len > 0) {
            int64_t take = len;
            if (op + take + 6 > cap) return false;
            if (take <= 60) {
                dst[op++] = (uint8_t)((take - 1) << 2);
            } else if (take - 1 <= 0xFF) {
                dst[op++] = 60 << 2;
                dst[op++] = (uint8_t)(take - 1);
            } else if (take - 1 <= 0xFFFF) {
                dst[op++] = 61 << 2;
                dst[op++] = (uint8_t)((take - 1) & 0xFF);
                dst[op++] = (uint8_t)((take - 1) >> 8);
            } else {
                take = 0x10000;  // chunk very long literals
                dst[op++] = 61 << 2;
                dst[op++] = 0xFF;
                dst[op++] = 0xFF;
            }
            memcpy(dst + op, src + from, take);
            op += take;
            from += take;
            len -= take;
        }
        return true;
    };
    enum { HB = 14 };
    static thread_local uint32_t htab[1u << HB];
    memset(htab, 0, sizeof(htab));
    auto hash = [](uint32_t x) { return (x * 0x1e35a7bd) >> (32 - HB); };
    auto rd32 = [&](int64_t p) {
        uint32_t x; memcpy(&x, src + p, 4); return x;
    };
    int64_t ip = 0, anchor = 0;
    while (ip + 4 <= n) {
        uint32_t h = hash(rd32(ip));
        int64_t ref = (int64_t)htab[h] - 1;
        htab[h] = (uint32_t)(ip + 1);
        if (ref < 0 || ip - ref > 65535 || rd32(ref) != rd32(ip)) {
            ip++;
            continue;
        }
        int64_t mlen = 4;
        while (ip + mlen < n && src[ref + mlen] == src[ip + mlen]) mlen++;
        if (!emit_literal(anchor, ip - anchor)) return -1;
        int64_t off = ip - ref;
        int64_t rem = mlen;
        while (rem > 0) {
            int64_t take = rem > 64 ? 64 : rem;
            if (take < 4) break;  // tail shorter than a copy: literal it
            if (op + 3 > cap) return -1;
            dst[op++] = (uint8_t)(((take - 1) << 2) | 2);  // 2-byte copy
            dst[op++] = (uint8_t)(off & 0xFF);
            dst[op++] = (uint8_t)(off >> 8);
            rem -= take;
        }
        ip += mlen - rem;
        if (rem > 0) {  // leftover (<4) emitted as literal with what follows
            anchor = ip;
            continue;
        }
        anchor = ip;
    }
    if (!emit_literal(anchor, n - anchor)) return -1;
    return op;
}

int64_t lct_snappy_uncompressed_len(const uint8_t* src, int64_t n) {
    uint64_t len = 0;
    int shift = 0;
    for (int64_t i = 0; i < n && i < 10; i++) {
        len |= (uint64_t)(src[i] & 0x7F) << shift;
        if (!(src[i] & 0x80)) return (int64_t)len;
        shift += 7;
    }
    return -1;
}

int64_t lct_snappy_decompress(const uint8_t* src, int64_t n,
                              uint8_t* dst, int64_t cap) {
    int64_t ip = 0;
    // skip preamble
    while (ip < n && (src[ip] & 0x80)) ip++;
    if (ip++ >= n) return -1;
    int64_t op = 0;
    while (ip < n) {
        uint8_t tag = src[ip++];
        uint8_t type = tag & 3;
        if (type == 0) {  // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int extra = (int)len - 60;
                if (ip + extra > n) return -1;
                len = 0;
                for (int i = 0; i < extra; i++)
                    len |= (int64_t)src[ip + i] << (8 * i);
                len += 1;
                ip += extra;
            }
            if (ip + len > n || op + len > cap) return -1;
            memcpy(dst + op, src + ip, len);
            ip += len;
            op += len;
        } else {
            int64_t len, off;
            if (type == 1) {  // 1-byte offset copy
                if (ip >= n) return -1;
                len = ((tag >> 2) & 7) + 4;
                off = ((int64_t)(tag >> 5) << 8) | src[ip++];
            } else if (type == 2) {
                if (ip + 2 > n) return -1;
                len = (tag >> 2) + 1;
                off = src[ip] | ((int64_t)src[ip + 1] << 8);
                ip += 2;
            } else {
                if (ip + 4 > n) return -1;
                len = (tag >> 2) + 1;
                off = (int64_t)src[ip] | ((int64_t)src[ip + 1] << 8) |
                      ((int64_t)src[ip + 2] << 16) |
                      ((int64_t)src[ip + 3] << 24);
                ip += 4;
            }
            if (off == 0 || off > op || op + len > cap) return -1;
            for (int64_t i = 0; i < len; i++) dst[op + i] = dst[op + i - off];
            op += len;
        }
    }
    return op;
}

}  // extern "C"
